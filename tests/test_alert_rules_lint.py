"""Lint gate: the default alert rule set can never silently orphan
(ISSUE 6 satellite, sibling of test_lint_no_hot_sync.py).

An alert rule references metric families by NAME; renaming a metric in
code would leave the rule evaluating a family nobody writes — it would
simply never fire again, which is the worst possible failure mode for
an alerting layer.  This gate walks the package (and examples/) AST
collecting every literal metric-family name and its label keys from
``inc`` / ``set`` / ``observe`` / ``observe_histogram`` /
``set_buckets`` call sites, then asserts every default rule references
an emitted family with valid label keys, ordered finite windows, and —
for burn rules — an objective that is an exact bucket bound (so the
conservative straddling-bucket accounting never applies to stock
rules).
"""

import ast
import pathlib

import tf_operator_tpu
from tf_operator_tpu.utils.alerts import (
    BurnRateRule,
    ThresholdRule,
    default_rules,
    validate_rule,
)
from tf_operator_tpu.utils.metrics import DEFAULT_BUCKETS, SLO_BUCKETS

PKG_ROOT = pathlib.Path(tf_operator_tpu.__file__).parent
EXAMPLES = PKG_ROOT.parent / "examples"

#: metrics-registry write methods whose first positional arg is the
#: family name and whose keyword args (minus these control kwargs) are
#: label keys
_WRITERS = {"inc", "set", "observe", "observe_histogram", "set_buckets"}
_CONTROL_KWARGS = {"exemplar", "buckets"}

#: families built with f-strings from ledger prefixes
#: (utils/metrics.DispatchLedger / StepSyncLedger) — not collectable as
#: literals; _assert_prefixes_still_exist pins the prefixes against the
#: source so this table cannot go stale silently
_LEDGER_FAMILIES = {
    "serving_dispatch_total": {"phase"},
    "serving_dispatch_seconds": {"phase"},
    "train_sync_total": {"phase"},
    "train_sync_seconds": {"phase"},
    "train_sync_blocked_total": {"phase"},
}


def _assert_prefixes_still_exist():
    src = (PKG_ROOT / "utils" / "metrics.py").read_text()
    for prefix in ("serving_dispatch", "train_sync"):
        assert f'"{prefix}"' in src, (
            f"ledger prefix {prefix!r} gone from utils/metrics.py — "
            "update _LEDGER_FAMILIES in this lint"
        )


def collect_emitted_families():
    """{family: set(label keys)} for every literal registry write in
    the package + examples."""

    families = {k: set(v) for k, v in _LEDGER_FAMILIES.items()}
    paths = list(PKG_ROOT.rglob("*.py")) + list(EXAMPLES.glob("*.py"))
    for path in paths:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _WRITERS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                continue
            name = node.args[0].value
            keys = {
                kw.arg
                for kw in node.keywords
                if kw.arg is not None and kw.arg not in _CONTROL_KWARGS
            }
            families.setdefault(name, set()).update(keys)
    return families


def test_default_rules_reference_live_metrics():
    _assert_prefixes_still_exist()
    families = collect_emitted_families()
    problems = []
    for rule in default_rules():
        validate_rule(rule)  # shape: windows ordered, thresholds finite
        if rule.metric not in families:
            problems.append(
                f"rule {rule.name!r} references {rule.metric!r} which no "
                "code emits"
            )
            continue
        unknown = set(rule.labels) - families[rule.metric]
        if unknown:
            problems.append(
                f"rule {rule.name!r} filters on label keys {sorted(unknown)} "
                f"never attached to {rule.metric!r} "
                f"(emitted keys: {sorted(families[rule.metric])})"
            )
    assert not problems, "orphaned alert rules:\n  " + "\n  ".join(problems)


def test_burn_objectives_are_exact_bucket_bounds():
    """objective_le must be a bound of the bucket set its family uses,
    or the straddling bucket silently counts as bad (conservative but
    surprising).  Stock families use SLO_BUCKETS or DEFAULT_BUCKETS."""

    bounds = set(SLO_BUCKETS) | set(DEFAULT_BUCKETS)
    for rule in default_rules():
        if isinstance(rule, BurnRateRule):
            assert rule.objective_le in bounds, (
                f"rule {rule.name!r}: objective_le={rule.objective_le} is "
                "not an exact bucket bound"
            )


def test_default_rule_names_unique_and_windows_parameterized():
    rules = default_rules(short=7.0, long=11.0)
    names = [r.name for r in rules]
    assert len(names) == len(set(names))
    for r in rules:
        if isinstance(r, BurnRateRule):
            assert r.windows == (7.0, 11.0)
        elif r.kind == "counter_increase":
            # window is a counter_increase concept only: gauge kinds
            # evaluate instantaneous snapshots (see ThresholdRule)
            assert r.window in (7.0, 11.0)


def test_collector_sees_known_call_sites():
    """The AST collector itself works: families written across the
    stack are found with their label keys."""

    families = collect_emitted_families()
    # watchdog (utils/watchdog.py)
    assert "heartbeat" in families["watchdog_stall_total"]
    # operator API (server/api.py)
    assert {"method", "route"} <= families["api_request_seconds"]
    # serving plane (examples/serve_lm.py + models/batching.py)
    assert {"route", "model"} <= families["serve_request_seconds"]
    assert "serve_admission_queue_depth" in families
    # retry clients (backend/retry.py)
    assert "client" in families["api_client_circuit_open_total"]
    # checkpointer durability stamp (parallel/checkpoint.py)
    assert "checkpoint_last_success_unix" in families
    # paged KV serving (models/batching.py + prefix_cache.py, ISSUE 8):
    # the kv-blocks-pressure rule and the rebound serving policy bind
    # these — the keys must stay declared at the literal call sites
    assert {"model", "replica"} <= families["kv_blocks_pressure"]
    assert {"model", "replica"} <= families["kv_blocks_free"]
    # ISSUE 10: the queued-demand component of the pressure ramp
    assert {"model", "replica"} <= families["kv_blocks_queued_demand"]
    assert "mode" in families["serve_prefix_cache_hits_total"]
    assert "mode" in families["serve_prefix_cache_evictions_total"]
    # ISSUE 12: the preemption/swap plane — the preemption-rate rule
    # and the committed-vs-reserved split bind these literal sites
    assert {"model", "tier"} <= families["serve_preemptions_total"]
    assert "direction" in families["kv_swap_bytes_total"]
    assert {"model", "replica"} <= families["kv_blocks_committed"]
    assert {"model", "replica"} <= families["kv_blocks_reserved"]
    # tier-labeled SLO histograms: /slo per-tier quantiles depend on
    # the pool's literal observation sites carrying the tier key
    assert "tier" in families["serve_ttft_seconds"]
    assert "tier" in families["serve_time_per_output_token_seconds"]
    assert "tier" in families["serve_queue_wait_seconds"]
    # ISSUE 13: the disaggregated serving plane — the role-filtered
    # stock policies bind kv_blocks_pressure{role=}, and the fabric
    # transport's own families must stay declared at literal sites
    assert {"model", "replica", "role"} <= families["kv_blocks_pressure"]
    assert {"model", "replica", "role"} <= families["kv_blocks_free"]
    assert "direction" in families["kv_migrate_bytes_total"]
    assert "model" in families["kv_fabric_blocks"]
    assert "model" in families["kv_fabric_publishes_total"]
    assert "model" in families["serve_fabric_publish_failures_total"]
    # ISSUE 17: the cross-pod fabric wire — migrate bytes split by
    # transport (local store vs HTTP pull), remote-pull outcomes and
    # failure reasons, per-peer liveness.  The fabric-peer-unreachable
    # rule and the dashboard fabric panel bind these literal sites.
    assert {"direction", "transport"} <= families["kv_migrate_bytes_total"]
    assert {"model", "outcome"} <= families["kv_fabric_pulls_total"]
    assert {"model", "reason"} <= families["kv_fabric_pull_failures_total"]
    assert "peer" in families["kv_fabric_peer_up"]
    # ISSUE 14: the multi-slice grad-sync plane — per-fabric byte and
    # collective counters (parallel/trainer.py host-side accounting),
    # the probe-measured sync-seconds histogram (parallel/collectives),
    # and the slice-loss signal the stock TPU_SLICE policy binds
    # (controller/reconciler.py gang sync)
    assert "fabric" in families["train_dcn_bytes_total"]
    assert "fabric" in families["train_dcn_collectives_total"]
    assert "fabric" in families["train_dcn_sync_seconds"]
    assert "job" in families["tpujob_gang_waiting_replicas"]


def collect_federated_families():
    """``collect_emitted_families`` plus the FEDERATION decoration
    (ISSUE 15): at the operator, every pod-emitted family is ALSO
    reachable with the scraper's ``{job, replica_type, replica_index,
    slice}`` labels on top of its own — so rules/policies/dashboards
    may filter on those keys without orphaning.  The decoration tuple
    is imported from the scraper (single source of truth); its shape
    is pinned below."""

    from tf_operator_tpu.controller.telemetry import FEDERATED_LABELS

    families = collect_emitted_families()
    return {
        name: keys | set(FEDERATED_LABELS)
        for name, keys in families.items()
    }


def test_federated_label_decoration_is_pinned():
    """ISSUE 15: the federated decoration the scraper stamps on every
    merged series — the keys the /federate exposition, the fleet
    dashboard panel, and any job-scoped rule filter key on.  Renaming
    one fails tier-1 here before it silently orphans a consumer."""

    from tf_operator_tpu.controller.telemetry import (
        FEDERATED_LABELS,
        ScrapeTarget,
    )

    assert FEDERATED_LABELS == (
        "job", "replica_type", "replica_index", "slice"
    )
    # the decoration really is what targets produce (the merge sites
    # spread ScrapeTarget.labels, so this pins the runtime shape)
    t = ScrapeTarget(
        job="default/j", replica_type="worker", replica_index=0,
        slice_id="1", url="http://127.0.0.1:1",
    )
    assert set(t.labels) == set(FEDERATED_LABELS)


def test_collector_sees_telemetry_call_sites():
    """ISSUE 15 satellite: the scrape-honesty meta families are
    emitted at literal call sites with the pinned label keys —
    ``telemetry_scrape_failures_total{job,replica}`` and the
    per-target ``telemetry_scrape_age_seconds`` carrying the full
    federated identity."""

    families = collect_emitted_families()
    assert {"job", "replica"} <= families["telemetry_scrape_failures_total"]
    assert {"job", "replica_type", "replica_index", "slice"} <= families[
        "telemetry_scrape_age_seconds"
    ]


def test_checkpoint_stale_rule_matches_federated_series():
    """ISSUE 15 satellite (the PR-6 process-scope gap, closed): the
    stock checkpoint-age rule must keep matching the FEDERATED
    ``checkpoint_last_success_unix{job=,...}`` series a subprocess
    trainer pod's scrape mirrors into the operator registry.  The rule
    matches by label-subset, so it may not grow a filter on keys the
    federated decoration doesn't carry — and the family must stay
    emitted pod-side."""

    from tf_operator_tpu.controller.telemetry import FEDERATED_LABELS

    families = collect_federated_families()
    rule = next(r for r in default_rules() if r.name == "checkpoint-stale")
    assert rule.metric == "checkpoint_last_success_unix"
    assert rule.kind == "gauge_age"
    assert rule.metric in families
    # any filter must resolve against pod-side keys + the decoration
    assert set(rule.labels) <= families[rule.metric]
    assert set(FEDERATED_LABELS) <= families[rule.metric]


#: ISSUE 16: the fleet scheduler's exposition contract — every family
#: controller/scheduler.py emits, with its EXACT label keys.  The
#: gang-queue-stall rule, the quota gauges the dashboard reads, and the
#: soak's decision accounting all key on these names; the gate below
#: pins them BOTH WAYS (a renamed family fails, and a new scheduler_*
#: family must be declared here before it ships).
SCHEDULER_FAMILIES = {
    "scheduler_admitted_total": set(),
    "scheduler_evaluations_total": set(),
    "scheduler_preemptions_total": {"victim_priority", "reason"},
    "scheduler_skipped_total": {"reason"},
    "scheduler_queue_position": {"job"},
    "scheduler_queued_since_unix": {"job"},
    "scheduler_quota_used_chips": {"quota"},
    "scheduler_quota_limit_chips": {"quota"},
}


def test_scheduler_families_pinned_both_ways():
    """ISSUE 16 satellite: the scheduler metric families are pinned in
    both directions — every declared family is emitted at a literal
    call site with exactly the declared label keys (rename or label
    drift fails tier-1), and no undeclared ``scheduler_*`` family can
    ship (additions must extend the pin table, i.e. be deliberate)."""

    families = collect_emitted_families()
    problems = []
    for name, keys in SCHEDULER_FAMILIES.items():
        if name not in families:
            problems.append(f"declared family {name!r} is never emitted")
        elif families[name] != keys:
            problems.append(
                f"family {name!r} emitted with keys "
                f"{sorted(families[name])}, pinned {sorted(keys)}"
            )
    undeclared = {
        n for n in families if n.startswith("scheduler_")
    } - set(SCHEDULER_FAMILIES)
    if undeclared:
        problems.append(
            f"undeclared scheduler_* families emitted: {sorted(undeclared)}"
        )
    assert not problems, (
        "scheduler exposition drift:\n  " + "\n  ".join(problems)
    )


#: ISSUE 17: the cross-pod KV fabric's exposition contract — every
#: ``kv_fabric_*`` family the fabric tier emits (prefix_cache.py
#: publish-side + models/fabric_service.py pull-side), with its EXACT
#: label keys.  The fabric-peer-unreachable rule, the dashboard fabric
#: panel, and the soak's decision accounting key on these names; the
#: gate below pins them BOTH WAYS.
FABRIC_FAMILIES = {
    "kv_fabric_blocks": {"model"},
    "kv_fabric_publishes_total": {"model"},
    "kv_fabric_pulls_total": {"model", "outcome"},
    "kv_fabric_pull_failures_total": {"model", "reason"},
    "kv_fabric_peer_up": {"peer"},
}


def test_fabric_families_pinned_both_ways():
    """ISSUE 17 satellite: the fabric metric families are pinned in both
    directions — every declared family is emitted at a literal call site
    with exactly the declared label keys (rename or label drift fails
    tier-1), and no undeclared ``kv_fabric_*`` family can ship
    (additions must extend the pin table, i.e. be deliberate)."""

    families = collect_emitted_families()
    problems = []
    for name, keys in FABRIC_FAMILIES.items():
        if name not in families:
            problems.append(f"declared family {name!r} is never emitted")
        elif families[name] != keys:
            problems.append(
                f"family {name!r} emitted with keys "
                f"{sorted(families[name])}, pinned {sorted(keys)}"
            )
    undeclared = {
        n for n in families if n.startswith("kv_fabric_")
    } - set(FABRIC_FAMILIES)
    if undeclared:
        problems.append(
            f"undeclared kv_fabric_* families emitted: {sorted(undeclared)}"
        )
    assert not problems, (
        "fabric exposition drift:\n  " + "\n  ".join(problems)
    )


def test_fabric_peer_unreachable_rule_binds_the_failure_counter():
    """ISSUE 17 satellite: the stock peer-health rule fires on any
    ``peer_dead`` pull failure — counter_increase over
    ``kv_fabric_pull_failures_total{reason="peer_dead"}`` — so a pod
    that keeps recomputing because its peer's socket resets pages a
    ticket instead of silently eating the latency."""

    rule = next(
        r for r in default_rules() if r.name == "fabric-peer-unreachable"
    )
    assert rule.metric == "kv_fabric_pull_failures_total"
    assert rule.kind == "counter_increase"
    assert rule.labels == {"reason": "peer_dead"}
    assert rule.metric in collect_emitted_families()


def test_gang_queue_stall_rule_binds_the_queue_stamp():
    """ISSUE 16 satellite: the stock starvation rule evaluates age over
    the scheduler's stable queued-since stamp — gauge_age over
    ``scheduler_queued_since_unix`` — so an empty queue (gauge cleared
    on admit/forget) never breaches and the oldest parked gang drives
    the measured age."""

    rule = next(r for r in default_rules() if r.name == "gang-queue-stall")
    assert rule.metric == "scheduler_queued_since_unix"
    assert rule.kind == "gauge_age"
    assert rule.metric in collect_emitted_families()


#: ISSUE 20: the device cost plane's exposition contract — every
#: family utils/costplane.py emits (compile ledger, HBM accountant,
#: step-time sentinel), with its EXACT label keys.  The compile-storm
#: and step-time-regression stock rules, the dashboard cost-plane
#: panel, `tpujob top`, and the autoscaler's cost-plane veto all key
#: on these names; the gate below pins them BOTH WAYS across the
#: ``compile_* `` / ``hbm_*`` / ``step_time_*`` prefixes.
COSTPLANE_FAMILIES = {
    "compile_total": {"program", "trigger"},
    "compile_seconds": {"program"},
    "hbm_component_bytes": {"device", "component"},
    "hbm_device_limit_bytes": {"device"},
    "hbm_headroom_bytes": {"device"},
    "step_time_p50_seconds": {"signal"},
    "step_time_p99_seconds": {"signal"},
    "step_time_drift_ratio": {"signal"},
}


def test_costplane_families_pinned_both_ways():
    """ISSUE 20 satellite: the cost-plane metric families are pinned in
    both directions — every declared family is emitted at a literal
    call site with exactly the declared label keys (rename or label
    drift fails tier-1), and no undeclared ``compile_*`` / ``hbm_*`` /
    ``step_time_*`` family can ship (additions must extend the pin
    table, i.e. be deliberate)."""

    families = collect_emitted_families()
    problems = []
    for name, keys in COSTPLANE_FAMILIES.items():
        if name not in families:
            problems.append(f"declared family {name!r} is never emitted")
        elif families[name] != keys:
            problems.append(
                f"family {name!r} emitted with keys "
                f"{sorted(families[name])}, pinned {sorted(keys)}"
            )
    undeclared = {
        n for n in families
        if n.startswith(("compile_", "hbm_", "step_time_"))
    } - set(COSTPLANE_FAMILIES)
    if undeclared:
        problems.append(
            f"undeclared cost-plane families emitted: {sorted(undeclared)}"
        )
    assert not problems, (
        "cost-plane exposition drift:\n  " + "\n  ".join(problems)
    )


def test_compile_storm_rule_binds_the_compile_counter():
    """ISSUE 20 satellite: the stock recompile-storm rule is
    counter_increase over ``compile_total`` — a fleet fragmenting into
    new width/K classes pages before the latency cliff does, and the
    autoscaler refuses to scale on the churn (COST_PLANE_VETO_RULES)."""

    rule = next(r for r in default_rules() if r.name == "compile-storm")
    assert rule.metric == "compile_total"
    assert rule.kind == "counter_increase"
    assert rule.severity == "page"
    assert rule.metric in collect_emitted_families()


def test_step_time_regression_rule_binds_the_drift_gauge():
    """ISSUE 20 satellite: the stock regression rule evaluates the
    sentinel's p50 drift RATIO gauge (rolling median over the frozen
    reference median) — the median, not the tail, so CI-box p99 jitter
    cannot false-positive it."""

    rule = next(
        r for r in default_rules() if r.name == "step-time-regression"
    )
    assert rule.metric == "step_time_drift_ratio"
    assert rule.kind == "gauge"
    assert rule.metric in collect_emitted_families()


def collect_dispatch_phases():
    """{phase literal: [site, ...]} for every literal first-arg
    ``<ledger>.dispatch("<phase>", ...)`` call in the package +
    examples — the same AST-collector pattern as
    collect_emitted_families, aimed at the serving span taxonomy."""

    phases = {}
    paths = list(PKG_ROOT.rglob("*.py")) + list(EXAMPLES.glob("*.py"))
    for path in paths:
        tree = ast.parse(path.read_text(), filename=str(path))
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "dispatch"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                phases.setdefault(node.args[0].value, []).append(
                    f"{path.name}:{node.lineno}"
                )
    return phases


def test_dispatch_phase_literals_match_span_taxonomy():
    """ISSUE 11 satellite: every DispatchLedger phase literal in the
    code must appear in the declared ``DISPATCH_PHASES`` taxonomy and
    vice versa.  The ledger derives each phase's span name as
    ``dispatch.<phase>``, and the request-autopsy waterfall, the
    dashboard SLO panel, and the per-request dispatch counts key on
    those literals — a renamed phase would orphan them all silently,
    so the rename must fail tier-1 instead."""

    from tf_operator_tpu.utils.metrics import DISPATCH_PHASES

    emitted = collect_dispatch_phases()
    declared = set(DISPATCH_PHASES)
    unknown = set(emitted) - declared
    assert not unknown, (
        "dispatch phases emitted but missing from "
        "utils/metrics.DISPATCH_PHASES (their dispatch.<phase> spans "
        "would be orphans to the autopsy/waterfall layers): "
        + ", ".join(
            f"{p} ({', '.join(emitted[p])})" for p in sorted(unknown)
        )
    )
    orphaned = declared - set(emitted)
    assert not orphaned, (
        "DISPATCH_PHASES declares phases no code dispatches (stale "
        "taxonomy — remove them or restore the emitter): "
        + ", ".join(sorted(orphaned))
    )


def test_every_declared_phase_lowers_to_a_dispatch_span():
    """The other half of the contract: dispatching any declared phase
    really does emit a ``dispatch.<phase>`` span (the ledger's
    span_prefix is part of the taxonomy, not an implementation
    detail)."""

    from tf_operator_tpu.utils.metrics import DISPATCH_PHASES, DispatchLedger
    from tf_operator_tpu.utils.trace import Tracer

    tracer = Tracer(seed=0)
    finished = []
    tracer.on_finish = finished.append
    ledger = DispatchLedger(tracer=tracer)
    for phase in DISPATCH_PHASES:
        with ledger.dispatch(phase):
            pass
    assert {s.name for s in finished} == {
        f"dispatch.{p}" for p in DISPATCH_PHASES
    }


def test_phase_collector_catches_a_renamed_phase():
    """The gate's own regression test: a phase literal outside the
    taxonomy is reported (plant the rename the gate exists for)."""

    from tf_operator_tpu.utils.metrics import DISPATCH_PHASES

    planted = ast.parse(
        "def f(self):\n"
        "    with self.ledger.dispatch('admit_v2'):\n"
        "        pass\n"
    )
    found = set()
    for node in ast.walk(planted):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "dispatch"
            and node.args
            and isinstance(node.args[0], ast.Constant)
        ):
            found.add(node.args[0].value)
    assert found == {"admit_v2"}
    assert not found <= set(DISPATCH_PHASES)


def test_lint_catches_a_renamed_metric():
    """Planted orphan: a rule naming a family nobody emits must be
    reported (the gate's own regression test)."""

    families = collect_emitted_families()
    ghost = ThresholdRule(
        "ghost", "metric_that_was_renamed_total", kind="counter_increase"
    )
    validate_rule(ghost)
    assert ghost.metric not in families
