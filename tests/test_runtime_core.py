"""Unit tests for the job-controller runtime primitives: work queue,
expectations, metrics (SURVEY.md §2 "Generic job-controller runtime").

One contract suite runs against BOTH implementations — the Python twins
and the native C++ runtime (tf_operator_tpu/native) — keeping them in
lockstep; the controller can be backed by either.
"""

import threading
import time

import pytest

from tf_operator_tpu import native
from tf_operator_tpu.controller.expectations import Expectations
from tf_operator_tpu.controller.workqueue import WorkQueue
from tf_operator_tpu.utils.metrics import Metrics

_HAVE_NATIVE = native.available()
_skip_native = pytest.mark.skipif(
    not _HAVE_NATIVE, reason=f"native runtime unavailable: {native.load_error()}"
)

WQ_IMPLS = [
    pytest.param(WorkQueue, id="python"),
    pytest.param(native.NativeWorkQueue if _HAVE_NATIVE else None,
                 id="native", marks=_skip_native),
]
EXP_IMPLS = [
    pytest.param(Expectations, id="python"),
    pytest.param(native.NativeExpectations if _HAVE_NATIVE else None,
                 id="native", marks=_skip_native),
]


@pytest.fixture(params=WQ_IMPLS)
def WQ(request):
    return request.param


@pytest.fixture(params=EXP_IMPLS)
def EXP(request):
    return request.param


class TestWorkQueue:
    def test_dedup(self, WQ):
        q = WQ()
        q.add("a")
        q.add("a")
        q.add("b")
        assert q.get(0) == "a"
        assert q.get(0) == "b"
        assert q.get(0) is None

    def test_dirty_reprocess(self, WQ):
        q = WQ()
        q.add("a")
        key = q.get(0)
        q.add("a")  # re-added while processing → dirty
        assert q.get(0) is None  # not yet
        q.done(key)
        assert q.get(0) == "a"  # reprocessed exactly once
        q.done("a")
        assert q.get(0) is None

    def test_add_after(self, WQ):
        q = WQ()
        q.add_after("a", 0.05)
        assert q.get(0) is None
        assert q.get(0.5) == "a"

    def test_rate_limited_backoff_grows(self, WQ):
        # jitter=False pins the exact exponential delays; the native
        # queue is jitterless by construction
        q = (
            WQ(base_delay=0.01, max_delay=1.0, jitter=False)
            if WQ is WorkQueue
            else WQ(base_delay=0.01, max_delay=1.0)
        )
        d1 = q.add_rate_limited("a")
        d2 = q.add_rate_limited("a")
        d3 = q.add_rate_limited("a")
        assert d1 < d2 < d3
        q.forget("a")
        assert q.num_requeues("a") == 0

    def test_rate_limited_full_jitter_bounded_and_seeded(self):
        """Python queue default: full jitter — each delay lands in
        [0, min(base*2^n, max)], the requeue count still grows, and a
        seeded rng replays the exact sequence (deterministic tests)."""

        import random

        q = WorkQueue(base_delay=0.01, max_delay=1.0, rng=random.Random(7))
        delays = [q.add_rate_limited("a") for _ in range(4)]
        for n, d in enumerate(delays):
            assert 0.0 <= d <= min(0.01 * 2**n, 1.0)
        assert q.num_requeues("a") == 4
        q2 = WorkQueue(base_delay=0.01, max_delay=1.0, rng=random.Random(7))
        assert [q2.add_rate_limited("a") for _ in range(4)] == delays

    def test_get_blocks_until_add(self, WQ):
        q = WQ()
        got = []

        def worker():
            got.append(q.get(2.0))

        t = threading.Thread(target=worker)
        t.start()
        time.sleep(0.05)
        q.add("x")
        t.join(timeout=2.0)
        assert got == ["x"]

    def test_shutdown_unblocks(self, WQ):
        q = WQ()
        got = []
        t = threading.Thread(target=lambda: got.append(q.get(None)))
        t.start()
        time.sleep(0.05)
        q.shutdown()
        t.join(timeout=2.0)
        assert got == [None]


class TestExpectations:
    def test_satisfied_lifecycle(self, EXP):
        e = EXP()
        assert e.satisfied("k")
        e.expect_creations("k", 2)
        assert not e.satisfied("k")
        e.creation_observed("k")
        assert not e.satisfied("k")
        e.creation_observed("k")
        assert e.satisfied("k")

    def test_deletions_tracked_separately(self, EXP):
        e = EXP()
        e.expect_creations("k", 1)
        e.expect_deletions("k", 1)
        e.creation_observed("k")
        assert not e.satisfied("k")
        e.deletion_observed("k")
        assert e.satisfied("k")

    def test_timeout_expires(self, EXP):
        e = EXP(timeout_s=0.01)
        e.expect_creations("k", 5)
        assert not e.satisfied("k")
        time.sleep(0.02)
        assert e.satisfied("k")  # assume events lost; self-heal

    def test_extra_observations_ignored(self, EXP):
        e = EXP()
        e.creation_observed("k")  # no expectation registered
        assert e.satisfied("k")
        assert e.pending("k") == (0, 0)


class TestMetrics:
    def test_counters_and_summary(self):
        m = Metrics()
        m.inc("jobs_total")
        m.inc("jobs_total")
        m.inc("pods_total", replica_type="worker")
        assert m.counter("jobs_total") == 2
        assert m.counter("pods_total", replica_type="worker") == 1
        for v in (1.0, 2.0, 3.0):
            m.observe("latency", v)
        s = m.summary("latency")
        assert s["count"] == 3 and s["mean"] == 2.0
        text = m.exposition()
        assert "jobs_total 2" in text
        assert 'pods_total{replica_type="worker"} 1' in text
