"""Continuous-batching decoder (models/batching.py).

The load-bearing property is SLOT ISOLATION: a request's tokens are
identical whether it runs alone in the pool or interleaved with other
concurrent requests — same code path, different occupancy, so the
assertion is exact (no tolerance).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # generation-loop compiles

from tf_operator_tpu.models import generate, gpt_tiny, llama_tiny
from tf_operator_tpu.models.batching import ContinuousBatchingDecoder

VOCAB = 96


def _tiny(family="llama"):
    make = {"llama": llama_tiny, "gpt": gpt_tiny}[family]
    model = make(vocab_size=VOCAB, max_len=48)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(1, 5)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    return model, params


def _prompts(n, lens):
    r = np.random.RandomState(7)
    return [r.randint(0, VOCAB, size=(l,)).astype(np.int32) for l in lens[:n]]


class TestSlotIsolation:
    def test_alone_equals_interleaved(self):
        model, params = _tiny()
        prompts = _prompts(3, [5, 9, 3])

        solo = []
        for p in prompts:
            dec = ContinuousBatchingDecoder(model, params, slots=4)
            rid = dec.submit(p, max_new_tokens=6)
            dec.run()
            solo.append(dec.result(rid))

        dec = ContinuousBatchingDecoder(model, params, slots=4)
        rids = [dec.submit(p, max_new_tokens=6) for p in prompts]
        dec.run()
        for rid, want in zip(rids, solo):
            np.testing.assert_array_equal(dec.result(rid), want)

    def test_staggered_arrivals(self):
        # a request submitted mid-flight joins the running loop and
        # still produces its solo tokens
        model, params = _tiny()
        p1, p2 = _prompts(2, [6, 4])

        ref = ContinuousBatchingDecoder(model, params, slots=2)
        r_ref = ref.submit(p2, max_new_tokens=5)
        ref.run()
        want = ref.result(r_ref)

        dec = ContinuousBatchingDecoder(model, params, slots=2)
        r1 = dec.submit(p1, max_new_tokens=10)
        for _ in range(3):
            dec.step()
        r2 = dec.submit(p2, max_new_tokens=5)
        dec.run()
        np.testing.assert_array_equal(dec.result(r2), want)
        assert dec.result(r1).shape == (6 + 10,)

    def test_more_requests_than_slots(self):
        model, params = _tiny()
        prompts = _prompts(5, [4, 6, 3, 5, 7])
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        rids = [dec.submit(p, max_new_tokens=4) for p in prompts]
        dec.run()
        for rid, p in zip(rids, prompts):
            out = dec.result(rid)
            assert out.shape == (p.size + 4,)
            np.testing.assert_array_equal(out[: p.size], p)

    def test_result_wait_blocks_until_done(self):
        import threading

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        p = _prompts(1, [5])[0]
        rid = dec.submit(p, max_new_tokens=4)
        assert dec.result_wait(rid, timeout=0.05) is None  # not stepped yet
        got = {}

        def waiter():
            got["row"] = dec.result_wait(rid, timeout=120)

        t = threading.Thread(target=waiter)
        t.start()
        dec.run()
        t.join(timeout=120)
        assert got["row"] is not None and got["row"].shape == (9,)

    def test_compile_count_constant_in_request_count(self):
        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        for p in _prompts(4, [5, 5, 5, 5]):
            dec.submit(p, max_new_tokens=3)
        dec.run()
        first = dec.compile_count
        for p in _prompts(4, [5, 5, 5, 5]):
            dec.submit(p, max_new_tokens=3)
        dec.run()
        assert dec.compile_count == first


class TestAgainstGenerate:
    def test_matches_generate_argmax_path(self):
        # generate() batches rows at equal positions; the pool vmaps
        # batch-1 — same math, so greedy tokens should agree on the
        # well-separated logits of a trained-ish tiny model.  Exactness
        # is asserted for the pool's own paths (TestSlotIsolation);
        # here shape + prompt echo + greedy determinism across runs.
        model, params = _tiny("gpt")
        p = _prompts(1, [5])[0]
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        rid = dec.submit(p, max_new_tokens=6)
        dec.run()
        out1 = dec.result(rid)

        dec2 = ContinuousBatchingDecoder(model, params, slots=2)
        rid2 = dec2.submit(p, max_new_tokens=6)
        dec2.run()
        np.testing.assert_array_equal(out1, dec2.result(rid2))
        ref = generate(
            model, params, jnp.asarray(p[None, :]), max_new_tokens=6
        )
        assert out1.shape == (np.asarray(ref).shape[1],)

    def test_temperature_sampling_deterministic_per_key(self):
        model, params = _tiny()
        p = _prompts(1, [4])[0]
        outs = []
        for _ in range(2):
            dec = ContinuousBatchingDecoder(model, params, slots=2)
            rid = dec.submit(
                p, max_new_tokens=5, temperature=0.8,
                rng=jax.random.PRNGKey(42),
            )
            dec.run()
            outs.append(dec.result(rid))
        np.testing.assert_array_equal(outs[0], outs[1])


class TestValidationAndQuant:
    def test_rejects_overflow_and_bad_args(self):
        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((0,), np.int32), max_new_tokens=2)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((40,), np.int32), max_new_tokens=20)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((4,), np.int32), max_new_tokens=2, temperature=-1)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((4,), np.int32), max_new_tokens=2, temperature=0.5)

    def test_top_k_one_equals_greedy(self):
        # top_k=1 leaves exactly one candidate: sampling at any
        # temperature must reproduce the greedy tokens — an exact
        # semantic pin on the per-slot top-k masking
        model, params = _tiny()
        p = _prompts(1, [6])[0]
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        greedy_rid = dec.submit(p, max_new_tokens=5)
        topk_rid = dec.submit(
            p, max_new_tokens=5, temperature=1.3, top_k=1,
            rng=jax.random.PRNGKey(3),
        )
        dec.run()
        np.testing.assert_array_equal(
            dec.result(topk_rid), dec.result(greedy_rid)
        )

    def test_top_k_validation(self):
        from tf_operator_tpu.models.batching import TOP_K_MAX

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        rng = jax.random.PRNGKey(0)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((4,), np.int32), 2, temperature=0.5,
                       top_k=0, rng=rng)
        with pytest.raises(ValueError):
            dec.submit(np.zeros((4,), np.int32), 2, temperature=0.5,
                       top_k=TOP_K_MAX + 1, rng=rng)

    def test_quantized_tree_slot_isolation(self):
        from tf_operator_tpu.ops.quant import quantize_tree

        model, params = _tiny()
        qparams = quantize_tree(params, min_size=1)
        solo = ContinuousBatchingDecoder(model, qparams, slots=2)
        p1, p2 = _prompts(2, [5, 7])
        rs = solo.submit(p1, max_new_tokens=4)
        solo.run()
        want = solo.result(rs)

        dec = ContinuousBatchingDecoder(model, qparams, slots=2)
        r1 = dec.submit(p1, max_new_tokens=4)
        r2 = dec.submit(p2, max_new_tokens=4)
        dec.run()
        np.testing.assert_array_equal(dec.result(r1), want)
        assert dec.result(r2) is not None

    def test_moe_family_slot_isolation(self):
        # routed experts decode droplessly per token; under the vmapped
        # slot step each row routes independently — occupancy must not
        # change a request's expert paths or tokens
        from tf_operator_tpu.models import moe_tiny

        model = moe_tiny(vocab_size=VOCAB, max_len=48)
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]
        prompts = _prompts(2, [6, 9])
        solo = []
        for p in prompts:
            dec = ContinuousBatchingDecoder(model, params, slots=2)
            rid = dec.submit(p, max_new_tokens=5)
            dec.run()
            solo.append(dec.result(rid))
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        rids = [dec.submit(p, max_new_tokens=5) for p in prompts]
        dec.run()
        for rid, want in zip(rids, solo):
            np.testing.assert_array_equal(dec.result(rid), want)

    def test_rolling_window_slot_isolation(self):
        # windowed model whose prompt EXCEEDS the window: admission
        # chunks cap at the window, per-slot wrap state stays
        # slot-local, and a request's tokens are occupancy-independent
        model = llama_tiny(vocab_size=VOCAB, max_len=48, window=8)
        r = np.random.RandomState(11)
        prompts = [
            r.randint(0, VOCAB, size=(l,)).astype(np.int32)
            for l in (13, 5, 21)  # 13 and 21 > window=8
        ]
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]

        solo = []
        for p in prompts:
            dec = ContinuousBatchingDecoder(model, params, slots=3)
            rid = dec.submit(p, max_new_tokens=5)
            dec.run()
            solo.append(dec.result(rid))

        dec = ContinuousBatchingDecoder(model, params, slots=3)
        rids = [dec.submit(p, max_new_tokens=5) for p in prompts]
        dec.run()
        for rid, want in zip(rids, solo):
            np.testing.assert_array_equal(dec.result(rid), want)


class TestConcurrencyStress:
    def test_threaded_submitters_with_driver_thread(self):
        # the serve_lm topology under load: N submitter threads racing
        # a driver thread; every request must complete, echo its
        # prompt, and honor its budget — no deadlocks, no lost slots
        import threading

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=3)
        stop = threading.Event()
        results = {}
        errors = []  # bound before the driver starts (drive closes over it)

        def drive():
            try:
                while not stop.is_set():
                    if dec.step() == 0:
                        stop.wait(0.002)
            except Exception as exc:  # surface the real decode failure
                errors.append(("driver", repr(exc)))
                stop.set()

        driver = threading.Thread(target=drive, daemon=True)
        driver.start()

        def submitter(tid):
            try:
                r = np.random.RandomState(tid)
                for j in range(4):
                    p = r.randint(0, VOCAB, size=(3 + (tid + j) % 5,)).astype(
                        np.int32
                    )
                    budget = 2 + (j % 3)
                    rid = dec.submit(p, max_new_tokens=budget)
                    row = dec.result_wait(rid, timeout=300)
                    assert row is not None
                    np.testing.assert_array_equal(row[: p.size], p)
                    assert row.shape == (p.size + budget,)
                    results[(tid, j)] = row
            except Exception as exc:  # surfaced below; threads must not die silently
                errors.append((tid, repr(exc)))

        threads = [
            threading.Thread(target=submitter, args=(t,)) for t in range(3)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        stop.set()
        driver.join(timeout=10)
        assert not errors, errors
        assert len(results) == 12


class TestSingleDispatchAdmission:
    def test_submit_is_host_only_and_never_blocks(self):
        """r6 admission fusion: submit() validates and queues — it
        never touches the device, even with every slot busy.  The
        whole admission (prefill + first token + seating) happens as
        one compiled dispatch inside _admit, and a budget-1 request
        completes at that admission without keeping a seat."""

        import time as _time

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        prompts = _prompts(4, [5, 7, 4, 6])
        r0 = dec.submit(prompts[0], max_new_tokens=30)
        r1 = dec.submit(prompts[1], max_new_tokens=30)
        dec.step()  # seats both; pool is now full
        admissions = dec.ledger.count("admission")
        t0 = _time.monotonic()
        r2 = dec.submit(prompts[2], max_new_tokens=3)
        r3 = dec.submit(prompts[3], max_new_tokens=1)
        assert _time.monotonic() - t0 < 60  # host-only, no blocking
        with dec._lock:
            # no device work at submit: nothing staged, no new dispatch
            assert all(r.staged_cache is None for r in dec._queue)
            assert all(len(r.tokens) == 0 for r in dec._queue)
        assert dec.ledger.count("admission") == admissions
        dec.run()
        # budget-1 completed at its single admission dispatch; it never
        # occupied a slot past it
        row3 = dec.result(r3)
        assert row3 is not None and row3.shape == (prompts[3].size + 1,)
        for rid, p, budget in ((r0, prompts[0], 30), (r1, prompts[1], 30),
                               (r2, prompts[2], 3)):
            row = dec.result(rid)
            np.testing.assert_array_equal(row[: p.size], p)
            assert row.shape == (p.size + budget,)

    def test_admission_is_exactly_one_dispatch_per_request(self):
        """The tentpole invariant (ISSUE 3): per-request admission
        device-dispatch count is EXACTLY 1 on the fused path — no
        chunked prefill dispatches, no sampling op group, no separate
        scatter.  The ledger counts real compiled-program calls; the
        legacy machinery must never have run (its jit caches stay
        empty), so the count cannot be satisfied by mislabeling."""

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        prompts = _prompts(5, [5, 9, 3, 5, 16])  # incl. an exact pow2
        rids = [dec.submit(p, max_new_tokens=4) for p in prompts]
        # a sampled request must ALSO admit in one dispatch (its rng
        # split happens in-graph)
        rids.append(
            dec.submit(prompts[0], max_new_tokens=4, temperature=0.7,
                       rng=jax.random.PRNGKey(5))
        )
        dec.run()
        assert dec.ledger.count("admission") == len(rids)
        assert dec.ledger.count("prefill") == 0
        assert dec.ledger.count("sample") == 0
        assert dec.ledger.count("scatter") == 0
        assert dec._prefill_fns == {} and dec._scatter_fn is None
        for rid in rids:
            assert dec.result(rid) is not None

    def test_slo_observations_per_request(self):
        """Every pooled request lands queue-wait + TTFT + time-per-
        output-token observations labeled {model, mode="pool"}, and
        the load gauges return to zero once the pool drains (ISSUE 5
        serving-SLO layer)."""

        from tf_operator_tpu.utils.metrics import Metrics

        model, params = _tiny()
        m = Metrics()
        dec = ContinuousBatchingDecoder(
            model, params, slots=2, metrics=m, model_label="llama"
        )
        prompts = _prompts(3, [5, 7, 4])
        rids = [dec.submit(p, max_new_tokens=4) for p in prompts]
        with dec._lock:
            # gauges live while queued: 3 requests x 4-token budgets
            assert m.gauge("serve_tokens_in_flight", model="llama") == 12.0
        dec.run()
        for rid in rids:
            assert dec.result(rid) is not None
        for fam in ("serve_queue_wait_seconds", "serve_ttft_seconds",
                    "serve_time_per_output_token_seconds"):
            # ISSUE 12: every pool SLO observation is tier-labeled
            # (default batch) so /slo reports per-tier quantiles
            assert m.histogram(
                fam, model="llama", mode="pool", tier="batch"
            )["count"] == 3, fam
        assert m.gauge("serve_admission_queue_depth", model="llama") == 0.0
        assert m.gauge("serve_tokens_in_flight", model="llama") == 0.0

    def test_admission_failure_requeues_request(self):
        """A transient device failure inside the fused admission must
        re-queue the request (the legacy prefill path's survival rule):
        a retried step() admits it and waiters never hang."""

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        real = dec._admission
        blown = []

        def flaky(width):
            fn = real(width)
            if not blown:
                blown.append(True)

                def boom(*a, **kw):
                    raise RuntimeError("RESOURCE_EXHAUSTED (simulated)")

                return boom
            return fn

        dec._admission = flaky
        p = _prompts(1, [5])[0]
        rid = dec.submit(p, max_new_tokens=3)
        with pytest.raises(RuntimeError):
            dec.step()
        with dec._lock:
            assert dec._queue and dec._queue[0].rid == rid  # requeued
        dec.run()  # retry succeeds
        out = dec.result(rid)
        assert out.shape == (p.size + 3,)
        np.testing.assert_array_equal(out[: p.size], p)

    def test_admission_compile_count_is_per_width_class(self):
        """One fused program per power-of-2 prompt-width class: prompts
        of length 5 and 7 share the width-8 program; 9 compiles 16."""

        model, params = _tiny()
        dec = ContinuousBatchingDecoder(model, params, slots=2)
        for p in _prompts(3, [5, 7, 9]):
            dec.submit(p, max_new_tokens=2)
        dec.run()
        assert sorted(dec._admit_fns) == [8, 16]

    def test_rolling_window_keeps_staged_path(self):
        """Rolling-window caches can't take the fused path (pad writes
        would poison cached_pos, and the wrap state is not index-
        rollbackable): they keep the legacy staged admission — eager
        submitter-thread prefill bounded by 2x-slots permits, burst
        overflow lazily primed, submit never blocking — and the ledger
        records it as prefill/sample/scatter, never as admission."""

        import time as _time

        model = llama_tiny(vocab_size=VOCAB, max_len=48, window=8)
        init = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), init)["params"]
        dec = ContinuousBatchingDecoder(model, params, slots=1)  # 2 permits
        prompts = _prompts(5, [4, 6, 3, 5, 13])
        t0 = _time.monotonic()
        rids = [dec.submit(p, max_new_tokens=3) for p in prompts]
        assert _time.monotonic() - t0 < 60  # no blocking on permits
        with dec._lock:
            staged = sum(r.staged_cache is not None for r in dec._queue)
            raw = sum(r.staged_cache is None for r in dec._queue)
        assert staged <= 2  # the permit bound held
        assert raw >= 3  # overflow took the lazy path
        dec.run()
        assert dec.ledger.count("admission") == 0
        assert dec.ledger.count("scatter") == len(prompts)
        assert dec.ledger.count("prefill") >= len(prompts)
        for rid, p in zip(rids, prompts):
            out = dec.result(rid)
            assert out.shape == (p.size + 3,)
            np.testing.assert_array_equal(out[: p.size], p)


class TestServeLmBatchingMode:
    def test_concurrent_http_requests_share_the_pool(self):
        import json
        import threading
        import urllib.request
        from http.server import ThreadingHTTPServer

        from tests.testutil import load_serve_lm

        serve_lm = load_serve_lm()
        model = llama_tiny(vocab_size=256, max_len=64)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        handler = serve_lm.build_handler(
            model, params, max_len=64, batching_slots=2
        )
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            results = {}

            def post(name, payload):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/generate",
                    data=json.dumps(payload).encode(),
                    method="POST",
                )
                with urllib.request.urlopen(req, timeout=300) as resp:
                    results[name] = json.loads(resp.read())

            threads = [
                threading.Thread(
                    target=post,
                    args=(i, {"prompt": f"req {i} ", "max_new_tokens": 6}),
                )
                for i in range(3)  # 3 requests > 2 slots: queueing too
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert set(results) == {0, 1, 2}
            for i in range(3):
                assert len(results[i]["sample"]) == 6
            # per-slot top_k sampling works through the pool...
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"prompt": "x", "max_new_tokens": 3, "top_k": 4,
                     "temperature": 0.7}
                ).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert len(json.loads(resp.read())["sample"]) == 3
            # ...but beyond the static width it is a loud 400
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(
                    {"prompt": "x", "max_new_tokens": 2, "top_k": 400,
                     "temperature": 0.7}
                ).encode(),
                method="POST",
            )
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError("oversize top_k not rejected")
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            server.shutdown()
