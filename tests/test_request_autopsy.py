"""Request autopsy for the paged serving plane (ISSUE 11): per-request
lifecycle tracing, the RequestLog / ArenaTimeline rings, flight-dump
sections, on-demand device profiling, and the acceptance e2e — one
request through a 2-replica paged pool over real HTTP yields a
complete autopsy at /requests/<id> with every lifecycle span under one
trace id and /debug/arena showing the occupancy rise and fall.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tf_operator_tpu.models.batching import RequestLog
from tf_operator_tpu.models.kv_blocks import ArenaTimeline
from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import DispatchLedger, Metrics
from tf_operator_tpu.utils.trace import TraceStore, Tracer

VOCAB = 96


class TestRequestLog:
    def _open(self, log, i, **kw):
        return log.open(
            id=f"t{i:04d}", rid=i, replica="0", model="m",
            prompt_tokens=4, max_new_tokens=8, **kw,
        )

    def test_bounded_fifo_eviction(self):
        log = RequestLog(capacity=4)
        for i in range(10):
            self._open(log, i)
        assert len(log) == 4
        assert log.evicted == 6
        assert log.get("t0000") is None
        assert log.get("t0009") is not None

    def test_entry_mutators_and_copies(self):
        log = RequestLog()
        e = self._open(log, 1)
        log.update(e, state="active", slot=2)
        log.count_dispatch(e, "admission")
        log.add_window(e, 5)
        log.add_window(e, 3)
        got = log.get("t0001")
        assert got["state"] == "active" and got["slot"] == 2
        assert got["windows"] == 2 and got["tokens"] == 8
        assert got["dispatches"] == {"admission": 1, "step": 2}
        # reads are copies: mutating the returned dict (or the entry
        # afterwards) never aliases the other side
        got["dispatches"]["step"] = 99
        assert log.get("t0001")["dispatches"]["step"] == 2

    def test_id_collision_keeps_both_autopsies(self):
        """A client reusing an x-trace-id must not silently destroy
        the first request's record: the plain id resolves to the
        newest request, the older one survives under <id>~<rid>
        (URL-unreserved separator — a '#' would be eaten as a URI
        fragment and make the demoted record unfetchable)."""

        log = RequestLog()
        first = log.open(id="tdup", rid=1, replica="0", model="m",
                         prompt_tokens=1, max_new_tokens=1)
        log.update(first, state="active")
        second = log.open(id="tdup", rid=2, replica="0", model="m",
                          prompt_tokens=1, max_new_tokens=1)
        assert log.get("tdup")["rid"] == 2
        assert log.get("tdup~1")["rid"] == 1
        assert log.get("tdup~1")["id"] == "tdup~1"  # listing-key parity
        assert log.get("tdup~1")["state"] == "active"
        # the demoted entry's dict is STILL the live one the pool
        # mutates — later lifecycle updates are not lost
        log.update(first, state="done")
        assert log.get("tdup~1")["state"] == "done"
        assert second is log._entries["tdup"]

    def test_eviction_protects_in_flight_entries(self):
        """Capacity pressure evicts FINISHED autopsies first: the
        long-running request an operator is actively debugging must
        not vanish from /requests/<id> because short requests churned
        past it."""

        log = RequestLog(capacity=4)
        live = self._open(log, 0)  # oldest, still in flight
        log.update(live, state="active")
        for i in range(1, 8):
            e = self._open(log, i)
            log.update(e, state="done")
        assert len(log) == 4
        assert log.get("t0000")["state"] == "active"  # survived churn
        # only done entries were evicted, oldest first
        assert log.get("t0001") is None
        # all-live logs still keep the bound (oldest-first fallback)
        flood = RequestLog(capacity=3)
        for i in range(6):
            flood.update(self._open(flood, i), state="active")
        assert len(flood) == 3
        assert flood.get("t0000") is None

    def test_recent_newest_first(self):
        log = RequestLog()
        for i in range(5):
            self._open(log, i)
        ids = [e["id"] for e in log.recent(3)]
        assert ids == ["t0004", "t0003", "t0002"]


class TestArenaTimeline:
    def test_bounded_ring_and_snapshot(self):
        tl = ArenaTimeline(capacity=8, block_size=16, usable=32,
                           replica="1")
        for i in range(20):
            tl.sample(free=32 - i, live=i, prefix_cached=min(i, 3),
                      queued_demand=0, seats_active=i % 4)
        assert len(tl) == 8
        assert tl.dropped == 12
        snap = tl.snapshot()
        assert snap["replica"] == "1" and snap["usable"] == 32
        assert snap["block_size"] == 16 and snap["dropped"] == 12
        assert len(snap["samples"]) == 8
        # oldest-first tail; limit takes the newest
        assert snap["samples"][-1]["live"] == 19
        assert [s["live"] for s in tl.tail(limit=2)] == [18, 19]
        json.dumps(snap)  # JSON-safe end to end


class TestFlightAutopsySections:
    """ISSUE 11 bugfix: alert/watchdog flight dumps carry the last-K
    request autopsies and the arena-timeline tail, after the existing
    sections (the determinism contract extends, never reorders)."""

    def _dump(self, rec):
        import io

        buf = io.StringIO()
        rec.dump(fileobj=buf)
        return [json.loads(x) for x in buf.getvalue().strip().splitlines()]

    def test_dump_carries_requests_and_arena_tail(self):
        rec = FlightRecorder(max_requests=3, max_arena_samples=4)
        log = RequestLog()
        for i in range(6):
            log.open(id=f"t{i}", rid=i, replica="0", model="m",
                     prompt_tokens=1, max_new_tokens=1)
        tl = ArenaTimeline(block_size=16, usable=8, replica="0")
        for i in range(10):
            tl.sample(free=8 - (i % 3), live=i % 3, prefix_cached=0,
                      queued_demand=0, seats_active=1)
        rec.attach_request_log(log)
        rec.attach_arena_timeline(tl)
        rec.record_log("WARN", "x", "episode")
        records = self._dump(rec)
        types = [r["type"] for r in records]
        # order: meta, then logs, then the new sections LAST
        assert types == ["meta", "log", "request", "request", "request",
                         "arena"]
        assert records[0]["requests"] == 3
        assert records[0]["arenaTimelines"] == 1
        # last-K means the NEWEST K requests, oldest-first in the dump
        assert [r["id"] for r in records if r["type"] == "request"] == [
            "t3", "t4", "t5",
        ]
        [arena] = [r for r in records if r["type"] == "arena"]
        assert len(arena["samples"]) == 4  # the tail, bounded

    def test_dump_merges_requests_across_logs_by_time(self):
        """Two replica logs, K-slot budget: the dump keeps the NEWEST
        K across BOTH logs (time-merged), not whichever log was
        attached last."""

        rec = FlightRecorder(max_requests=4)
        a, b = RequestLog(), RequestLog()
        for i in range(4):
            a.open(id=f"a{i}", rid=i, replica="0", model="m",
                   prompt_tokens=1, max_new_tokens=1,
                   submit_unix=float(2 * i))
            b.open(id=f"b{i}", rid=i, replica="1", model="m",
                   prompt_tokens=1, max_new_tokens=1,
                   submit_unix=float(2 * i + 1))
        rec.attach_request_log(a)
        rec.attach_request_log(b)
        ids = [r["id"] for r in rec.records() if r["type"] == "request"]
        # newest 4 of the interleaved timeline, oldest-first
        assert ids == ["a2", "b2", "a3", "b3"]

    def test_unattached_recorder_dump_shape_unchanged(self):
        rec = FlightRecorder()
        rec.record_log("INFO", "x", "m")
        assert [r["type"] for r in self._dump(rec)] == ["meta", "log"]


class TestProfileAndSurfaceEndpoints:
    """The host-side serving endpoints that need no pool: /debug/profile
    wraps jax.profiler and returns the artifact path; /requests and
    /debug/arena answer sanely in non-pool modes."""

    @pytest.fixture(scope="class")
    def server(self):
        from http.server import ThreadingHTTPServer

        import jax
        import jax.numpy as jnp

        from tests.testutil import load_serve_lm
        from tf_operator_tpu.models import llama_tiny

        serve_lm = load_serve_lm()
        model = llama_tiny(vocab_size=256, max_len=64)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        handler = serve_lm.build_handler(model, params, max_len=64)
        srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        yield f"http://127.0.0.1:{srv.server_address[1]}"
        srv.shutdown()

    def _get(self, url):
        with urllib.request.urlopen(url, timeout=30) as resp:
            return resp.status, json.loads(resp.read())

    def test_profile_returns_artifact_path(self, server, tmp_path,
                                           monkeypatch):
        import os

        monkeypatch.setenv("TPUJOB_PROFILE_DIR", str(tmp_path))
        code, body = self._get(server + "/debug/profile?seconds=0.1")
        assert code == 200
        assert body["seconds"] == 0.1
        assert body["artifact"].startswith(str(tmp_path))
        # the profiler really wrote a trace artifact under the dir
        found = [
            f for root, _, fs in os.walk(body["artifact"]) for f in fs
        ]
        assert found, "profile artifact directory is empty"

    def test_profile_validates_seconds(self, server):
        for bad in ("seconds=0", "seconds=31", "seconds=nope"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    server + "/debug/profile?" + bad, timeout=30
                )
            assert ei.value.code == 400

    def test_profile_path_is_exact(self, server):
        """A typo'd /debug/profileX must 404, never trigger a real
        device profile."""

        for path in ("/debug/profiler", "/debug/profileX"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(server + path, timeout=30)
            assert ei.value.code == 404

    def test_requests_and_arena_without_a_pool(self, server):
        code, body = self._get(server + "/requests")
        assert code == 200 and body == {"requests": []}
        code, body = self._get(server + "/debug/arena")
        # "fabric" is None outside disaggregated --roles (ISSUE 13)
        assert code == 200 and body == {"replicas": [], "fabric": None}
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(server + "/requests/tmissing",
                                   timeout=30)
        assert ei.value.code == 404


# ---------------------------------------------------------------------------
# pool-driving coverage (generation-loop compiles)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models import llama_tiny

    model = llama_tiny(vocab_size=VOCAB, max_len=64)
    params = model.init(
        jax.random.PRNGKey(1), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    return model, params


@pytest.mark.slow
class TestLifecycleThroughThePool:
    def test_paged_pool_emits_full_lifecycle(self, tiny_model):
        """Direct (no-HTTP) pin of the tentpole: a paged pool request
        gets queue.wait / admission / decode.window / retire spans on
        ITS trace id, a complete autopsy in the RequestLog, and the
        arena timeline records the occupancy swing."""

        from tf_operator_tpu.models.batching import (
            PagedContinuousBatchingDecoder,
        )

        model, params = tiny_model
        m = Metrics()
        tracer = Tracer(seed=0)
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16,
            ledger=DispatchLedger(metrics=m, tracer=tracer),
            metrics=m, model_label="tiny",
        )
        r = np.random.RandomState(0)
        rid = pool.submit(
            r.randint(0, VOCAB, size=(20,)).astype(np.int32), 14,
            trace_id="treqpin00001",
        )
        pool.run()
        assert pool.result(rid) is not None

        entry = pool.request_log.get("treqpin00001")
        assert entry["state"] == "done"
        assert entry["rid"] == rid and entry["replica"] == "0"
        assert entry["queue_wait_seconds"] is not None
        assert entry["ttft_seconds"] >= entry["queue_wait_seconds"]
        adm = entry["admission"]
        # 20 prompt + 14 budget at block 16 -> 3 blocks, no prefix hit
        assert adm["blocks_reserved"] == 3
        assert adm["prefix_hit_tokens"] == 0
        assert adm["prefill_dispatches"] == 0
        assert entry["windows"] >= 1
        assert entry["tokens"] == 14
        assert entry["dispatches"]["admission"] == 1
        assert entry["dispatches"]["step"] == entry["windows"]
        assert entry["dispatches"]["retire"] == 1
        # one full prompt block stays published in the prefix cache
        assert entry["retire"]["blocks_freed"] == 2

        trace = tracer.store.trace("treqpin00001")
        names = {s["name"] for s in trace["spans"]}
        assert {"queue.wait", "admission", "dispatch.admission",
                "decode.window", "retire"} <= names
        # the device dispatch nests under the lifecycle admission span
        by_name = {s["name"]: s for s in trace["spans"]}
        assert (by_name["dispatch.admission"]["parentId"]
                == by_name["admission"]["spanId"])
        assert by_name["admission"]["attributes"]["blocks_reserved"] == 3
        assert by_name["retire"]["attributes"]["blocks_freed"] == 2
        # every span carries the replica tag (satellite: dispatch spans
        # gain a replica attribute)
        assert by_name["dispatch.admission"]["attributes"]["replica"] == "0"

        # the arena timeline saw the occupancy rise and fall
        samples = pool.timeline.tail()
        lives = [s["live"] for s in samples]
        assert max(lives) >= 3
        assert lives[-1] == 1  # the published prefix block remains
        # SLO exemplars name this request's trace
        assert m.exemplar("serve_ttft_seconds") == "treqpin00001"

    def test_prefix_hit_depth_recorded(self, tiny_model):
        """A repeat prompt's autopsy carries the prefix-chain hit
        depth the admission actually used."""

        from tf_operator_tpu.models.batching import (
            PagedContinuousBatchingDecoder,
        )

        model, params = tiny_model
        pool = PagedContinuousBatchingDecoder(
            model, params, slots=2, kv_block_size=16,
            ledger=DispatchLedger(tracer=Tracer(seed=1)),
        )
        r = np.random.RandomState(3)
        prompt = r.randint(0, VOCAB, size=(36,)).astype(np.int32)
        first = pool.submit(prompt, 4, trace_id="tcold")
        pool.run()
        assert pool.result(first) is not None
        second = pool.submit(prompt, 4, trace_id="twarm")
        pool.run()
        assert pool.result(second) is not None
        cold = pool.request_log.get("tcold")["admission"]
        warm = pool.request_log.get("twarm")["admission"]
        assert cold["prefix_hit_tokens"] == 0
        assert warm["prefix_hit_tokens"] == 32  # 2 full blocks of 16
        assert warm["prefix_hit_blocks"] == 2
        assert warm["blocks_reserved"] == 3  # 2 shared + 1 fresh

    def test_trace_store_tail_sampling_under_sustained_load(
        self, tiny_model
    ):
        """ISSUE 11 satellite: a few hundred pool requests through a
        SMALL TraceStore — memory stays bounded at max_traces, and the
        protect-error-and-slow invariant holds end to end (the error
        and slow request traces survive the flood of ok-and-fast
        ones)."""

        from tf_operator_tpu.models.batching import (
            PagedContinuousBatchingDecoder,
        )

        store = TraceStore(max_traces=24, slow_seconds=30.0)
        tracer = Tracer(store=store, seed=2)
        pool = PagedContinuousBatchingDecoder(
            model=tiny_model[0], params=tiny_model[1], slots=4,
            steps_per_sync=4, kv_block_size=16,
            ledger=DispatchLedger(tracer=tracer),
        )
        r = np.random.RandomState(9)
        protected_err = []
        protected_slow = []
        total = 300
        for i in range(total):
            tid = f"tload{i:05d}"
            pool.submit(
                r.randint(0, VOCAB, size=(4 + i % 5,)).astype(np.int32),
                3, trace_id=tid,
            )
            if i % 40 == 0:
                # a failed request: its serve-span error status is what
                # tail sampling protects
                sp = tracer.start_span("serve.generate", trace_id=tid)
                sp.set_error("boom")
                sp.end()
                protected_err.append(tid)
            if i == total // 2:
                # a pathologically slow request (backdated span)
                sp = tracer.start_span(
                    "serve.generate", trace_id=tid,
                    start_mono=time.monotonic() - 60.0,
                )
                sp.end()
                protected_slow.append(tid)
            if i % 3 == 0:
                pool.step()
        pool.run()

        # bounded memory under ~10x max_traces of request traffic
        assert len(store) == 24
        # the protected traces survived the flood
        for tid in protected_err:
            t = store.trace(tid)
            assert t is not None and t["error"], tid
        for tid in protected_slow:
            t = store.trace(tid)
            assert t is not None and t["slow"], tid
        # and the autopsy ring stayed bounded too
        assert len(pool.request_log) == pool.request_log.capacity


@pytest.mark.slow
class TestAutopsyE2E:
    """ISSUE 11 acceptance: one request to a 2-replica paged pool over
    real HTTP yields a complete autopsy at /requests/<id> — queue.wait,
    admission (blocks reserved + prefix-hit depth), >=1 decode window,
    and retire all under ONE trace id, with the serving replica
    identified — and /debug/arena shows the block-occupancy rise and
    fall.  All recording is host-side; the no-hot-sync lint gate
    (tests/test_lint_no_hot_sync.py) runs unchanged in the same suite.
    """

    def test_http_autopsy_and_arena(self):
        from http.server import ThreadingHTTPServer

        import jax
        import jax.numpy as jnp

        from tests.testutil import load_serve_lm
        from tf_operator_tpu.models import llama_tiny

        serve_lm = load_serve_lm()
        model = llama_tiny(vocab_size=256, max_len=64)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        handler = serve_lm.build_handler(
            model, params, max_len=64, batching_slots=2, replicas=2
        )
        server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            tid = "treqe2e00001"
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps(
                    {"prompt": "autopsy this request ",
                     "max_new_tokens": 12}
                ).encode(),
                headers={"x-trace-id": tid},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                body = json.loads(resp.read())
                assert resp.headers["x-trace-id"] == tid
            # the request's first-class id is the adopted trace id
            assert body["request_id"] == tid
            assert len(body["sample"]) == 12

            # a second request so both replicas see traffic / the
            # router provably chose for each
            req2 = urllib.request.Request(
                base + "/generate",
                data=json.dumps(
                    {"prompt": "second ", "max_new_tokens": 4}
                ).encode(),
                method="POST",
            )
            with urllib.request.urlopen(req2, timeout=300) as resp:
                body2 = json.loads(resp.read())

            # ---- the autopsy, over HTTP, by request id
            with urllib.request.urlopen(
                base + f"/requests/{tid}", timeout=30
            ) as resp:
                autopsy = json.loads(resp.read())
            assert autopsy["id"] == tid
            assert autopsy["state"] == "done"
            assert autopsy["replica"] in ("0", "1")
            assert autopsy["queue_wait_seconds"] is not None
            adm = autopsy["admission"]
            assert adm["blocks_reserved"] >= 1
            assert adm["prefix_hit_tokens"] >= 0
            assert autopsy["windows"] >= 1
            assert autopsy["tokens"] == 12
            assert autopsy["retire"] is not None

            # ---- every lifecycle span under ONE trace id
            with urllib.request.urlopen(
                base + f"/traces/{tid}", timeout=30
            ) as resp:
                trace = json.loads(resp.read())
            names = {s["name"] for s in trace["spans"]}
            assert {"serve.generate", "route", "queue.wait", "admission",
                    "dispatch.admission", "decode.window",
                    "retire"} <= names
            assert all(s["traceId"] == tid for s in trace["spans"])
            route = next(s for s in trace["spans"] if s["name"] == "route")
            assert route["attributes"]["replica"] == autopsy["replica"]
            assert "load_score" in route["attributes"]

            # ---- /requests lists both, merged across replicas
            with urllib.request.urlopen(
                base + "/requests", timeout=30
            ) as resp:
                listing = json.loads(resp.read())["requests"]
            ids = {e["id"] for e in listing}
            assert {tid, body2["request_id"]} <= ids

            # ---- the arena timeline shows the rise and fall
            with urllib.request.urlopen(
                base + "/debug/arena", timeout=30
            ) as resp:
                arena = json.loads(resp.read())
            served = next(
                r for r in arena["replicas"]
                if r["replica"] == autopsy["replica"]
            )
            lives = [s["live"] for s in served["samples"]]
            assert max(lives) >= adm["blocks_reserved"]  # the rise
            assert lives[-1] < max(lives)                # the fall
        finally:
            server.shutdown()
