"""Lint gate: no raw blocking host↔device syncs in the training hot
path (ISSUE 4, the training twin of test_lint_no_bare_except.py).

The tentpole made steady-state training dispatch-free-running: every
device→host fetch in the step loop goes through
``StepSyncLedger.resolve()`` so it is counted, timed, and visible on
``/metrics`` as ``train_sync_*``.  A raw ``float(...)`` /
``np.asarray(...)`` / ``jax.device_get(...)`` / ``.block_until_ready()``
re-introduced into the step-loop bodies would silently bring back the
one-RTT-per-step serialization PR 4 removed — this AST walk keeps it
out.

Scope: the functions that ARE the step loop — ``train_loop`` in
runtime/harness.py and the train-step path in parallel/trainer.py
(``train_step`` / ``train_steps`` / the compiled bodies).  A forbidden
call is exempt only when its own arguments contain a ``.resolve(...)``
call (``float(ledger.resolve(...))`` — already host-side by
construction).  Measurement helpers (benchmark/_slope_time/hard_sync)
and eval are off the steady-state path and stay unlinted.

ISSUE 10 extends the gate to the SERVING paged step loop
(``PagedContinuousBatchingDecoder.step``/``_step`` in
models/batching.py — class-scoped so the legacy contiguous pool's
documented host work stays out of scope): steady-state paged decode
runs over device-resident tables with zero per-step uploads, so any
raw host gather there would quietly re-introduce the per-step traffic
the fused kernel removed.  The serving-side sanctioned fetch is the
one INSIDE a ``with ...dispatch(...)`` block — the DispatchLedger's
counting+timing wrapper, serving's equivalent of the training
ledger's ``.resolve(...)`` (the ledger contract says the in-block
fetch is part of the measured round trip).
"""

import ast
import pathlib

import tf_operator_tpu

PKG_ROOT = pathlib.Path(tf_operator_tpu.__file__).parent

#: (file, function names that constitute its step-loop hot path)
#: ISSUE 19 extends the gate to the fused-BatchNorm train path: the
#: pallas dispatch wrappers + custom_vjp fwd/bwd + the xla reference
#: (ops/fused_batchnorm.py) and the ResNet forward/validation
#: (models/resnet.py) all run inside the compiled train step — a raw
#: host fetch in any of them would serialize every ResNet step the
#: fusion exists to speed up.  The pallas kernel BODIES (_fwd_kernel /
#: _bwd_kernel) are deliberately out of scope: they execute on-device
#: where a host sync is structurally impossible, and their
#: ``float(n_rows)`` is a static Python grid int, not an array fetch.
HOT_FUNCTIONS = {
    "runtime/harness.py": {"train_loop"},
    "parallel/trainer.py": {
        "train_step",
        "train_steps",
        "_step_body",
        "_build_step",
        "_build_multi_step",
    },
    "ops/fused_batchnorm.py": {
        "_fwd_pallas",
        "_bwd_pallas",
        "_fusedbn_fwd",
        "_fusedbn_bwd",
        "_fusedbn_xla",
    },
    "models/resnet.py": {"__call__", "_resolve_norm"},
    # ISSUE 20 extends the gate to the step-time sentinel's sampling
    # path (utils/costplane.py): observe() runs inside the decode
    # window and the train loop with a wall-clock delta the callers
    # computed host-side — a float()/asarray() coercion here would let
    # a device scalar smuggle a blocking fetch into every single
    # steady-state window under the guise of "just recording a gauge"
    "utils/costplane.py": {"observe", "_quantiles"},
}

#: file -> {class name -> step-loop functions} (serving hot paths are
#: methods; class scoping keeps same-named base-class methods with
#: documented host work out of the gate).  ISSUE 12 extends the set
#: to the swap/lazy-allocation paths: growth runs in the per-window
#: host window and preemption/resume do real device→host copies —
#: every one of those copies must route through the sanctioned
#: ``with ...dispatch(...)`` window so it is counted, timed, and can
#: never silently serialize the steady-state step loop.
#: ISSUE 13 extends the set again to the MIGRATION planning paths:
#: fabric publishes (prefill side) and pulls (decode side) do real
#: device↔host block copies — every one must route through the
#: sanctioned ``with ...dispatch(...)`` window (migrate_out /
#: migrate_in) so disaggregation can never smuggle an uncounted sync
#: into admission planning.
#: ISSUE 18 extends the set to the SPECULATIVE step paths: the draft
#: scan, the fused verify, and the draft prefill all run inside the
#: per-window dispatch budget (1 draft + 1 verify per window is the
#: whole point) — a raw host fetch in any of them would hide an extra
#: round trip the draft/verify ledger phases exist to count.
HOT_CLASS_FUNCTIONS = {
    "models/batching.py": {
        "PagedContinuousBatchingDecoder": {
            "step", "_step", "_grow_seats_locked", "_alloc_blocks_locked",
            "_preempt_seat_locked", "_admit_swapped",
            "_plan_resume_locked", "_pick_victim_locked",
            "_demote_queued_locked",
            "_plan_admission", "_migrate_in_locked", "publish_to_fabric",
            "_spec_draft", "_spec_verify", "_draft_prefill_seat",
            "_draft_admission",
        },
    },
}

#: bare-name calls that force a device→host sync
FORBIDDEN_NAMES = {"float"}
#: attribute calls that force one (any receiver: np.asarray,
#: jax.device_get, arr.block_until_ready)
FORBIDDEN_ATTRS = {"asarray", "device_get", "block_until_ready"}


def _forbidden(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name) and f.id in FORBIDDEN_NAMES:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in FORBIDDEN_ATTRS:
        return f.attr
    return None


def _contains_resolve(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "resolve"
        for n in ast.walk(node)
    )


def _is_exempt(call: ast.Call) -> bool:
    args = list(call.args) + [kw.value for kw in call.keywords]
    return any(_contains_resolve(a) for a in args)


def _in_dispatch_block(node: ast.AST) -> bool:
    """True for a ``with <...>.dispatch(...)`` statement — the serving
    ledger's counting+timing wrapper (the sanctioned fetch window)."""

    if not isinstance(node, ast.With):
        return False
    for item in node.items:
        expr = item.context_expr
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "dispatch"
        ):
            return True
    return False


def _walk_fn(fn: ast.AST, label: str, offenders, allow_dispatch: bool):
    def visit(node, in_dispatch):
        if isinstance(node, ast.Call):
            name = _forbidden(node)
            if (
                name is not None
                and not _is_exempt(node)
                and not (allow_dispatch and in_dispatch)
            ):
                offenders.append(f"{label}:{node.lineno} {name}(...)")
        if allow_dispatch and _in_dispatch_block(node):
            # only the with BODY is inside the ledger's timed window;
            # the header (context_expr/optional_vars) evaluates BEFORE
            # the window opens — a sync there must stay flagged (the
            # serving twin of test_resolve_argument_interior_is_not_
            # exempt)
            for item in node.items:
                visit(item.context_expr, in_dispatch)
                if item.optional_vars is not None:
                    visit(item.optional_vars, in_dispatch)
            for child in node.body:
                visit(child, True)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, in_dispatch)

    for child in ast.iter_child_nodes(fn):
        visit(child, False)


def find_hot_syncs(tree: ast.AST, func_names, label: str,
                   allow_dispatch: bool = False):
    offenders = []
    for fn in ast.walk(tree):
        if (
            isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and fn.name in func_names
        ):
            _walk_fn(fn, label, offenders, allow_dispatch)
    return offenders


def find_hot_syncs_in_class(tree: ast.AST, class_map, label: str):
    """Class-scoped variant with the serving dispatch-window exemption
    (module docstring): only the named classes' named methods are
    walked."""

    offenders = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in class_map:
            funcs = class_map[node.name]
            for fn in node.body:
                if (
                    isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name in funcs
                ):
                    _walk_fn(
                        fn, f"{label}:{node.name}", offenders,
                        allow_dispatch=True,
                    )
    return offenders


def _lint_package():
    offenders = []
    for rel, funcs in sorted(HOT_FUNCTIONS.items()):
        path = PKG_ROOT / rel
        tree = ast.parse(path.read_text(), filename=str(path))
        offenders.extend(find_hot_syncs(tree, funcs, rel))
    for rel, class_map in sorted(HOT_CLASS_FUNCTIONS.items()):
        path = PKG_ROOT / rel
        tree = ast.parse(path.read_text(), filename=str(path))
        offenders.extend(find_hot_syncs_in_class(tree, class_map, rel))
    return offenders


def test_no_raw_syncs_in_training_hot_path():
    offenders = _lint_package()
    assert not offenders, (
        "raw blocking host<->device syncs in the training step loop — "
        "route them through StepSyncLedger.resolve() "
        "(utils/metrics.py):\n  " + "\n  ".join(offenders)
    )


def test_walker_catches_planted_syncs():
    """The gate itself works: each forbidden spelling is found inside a
    hot function, resolve-routed fetches are not, and functions outside
    the hot set are ignored."""

    src = (
        "def train_loop(trainer, batch):\n"
        "    for step in range(10):\n"
        "        m = trainer.train_step(batch)\n"
        "        a = float(m['loss'])\n"                 # offender
        "        b = np.asarray(m['loss'])\n"            # offender
        "        jax.device_get(m)\n"                    # offender
        "        m['loss'].block_until_ready()\n"        # offender
        "        ok = float(ledger.resolve('step', m['loss']))\n"  # exempt
        "        ok2 = np.asarray(ledger.resolve('w', m))\n"       # exempt
        "\n"
        "def evaluate(batches):\n"
        "    return [float(b) for b in batches]\n"       # not hot: ignored
    )
    offenders = find_hot_syncs(ast.parse(src), {"train_loop"}, "planted")
    assert [o.split()[1] for o in offenders] == [
        "float(...)", "asarray(...)", "device_get(...)",
        "block_until_ready(...)",
    ]


def test_paged_step_loop_collector_scopes_and_exempts():
    """The serving extension works: forbidden calls inside the paged
    class's step loop are flagged, the fetch inside a ``with
    ledger.dispatch(...)`` block is sanctioned, and the SAME method
    name on another class (the contiguous pool's documented host work)
    stays out of scope."""

    src = (
        "class PagedContinuousBatchingDecoder:\n"
        "    def step(self):\n"
        "        with self.ledger.dispatch('step'):\n"
        "            host_toks = np.asarray(toks_k)\n"       # sanctioned
        "        bad = np.asarray(self._tables_dev)\n"        # offender
        "        worse = float(lengths[0])\n"                 # offender
        "\n"
        "class ContinuousBatchingDecoder:\n"
        "    def step(self):\n"
        "        rngs = np.asarray(r)\n"                      # out of scope
    )
    offenders = find_hot_syncs_in_class(
        ast.parse(src),
        {"PagedContinuousBatchingDecoder": {"step"}},
        "planted",
    )
    assert [o.split()[1] for o in offenders] == [
        "asarray(...)", "float(...)",
    ]
    assert all("PagedContinuousBatchingDecoder" in o for o in offenders)


def test_dispatch_block_header_is_not_exempt():
    """A sync smuggled into the ``with ledger.dispatch(...)`` HEADER
    runs before the timed window opens — it must stay flagged even
    though the With body is sanctioned (the serving twin of
    test_resolve_argument_interior_is_not_exempt)."""

    src = (
        "class PagedContinuousBatchingDecoder:\n"
        "    def step(self):\n"
        "        with self.ledger.dispatch('step', n=float(x[0])):\n"  # offender
        "            ok = np.asarray(toks_k)\n"                        # sanctioned
    )
    offenders = find_hot_syncs_in_class(
        ast.parse(src),
        {"PagedContinuousBatchingDecoder": {"step"}},
        "planted",
    )
    assert len(offenders) == 1 and "float" in offenders[0]


def test_resolve_argument_interior_is_not_exempt():
    """``ledger.resolve('x', float(y))`` evaluates float(y) BEFORE the
    ledger sees anything — that interior sync must still be flagged."""

    src = (
        "def train_step(self, batch):\n"
        "    self.ledger.resolve('x', float(batch['y']))\n"
    )
    offenders = find_hot_syncs(ast.parse(src), {"train_step"}, "planted")
    assert len(offenders) == 1 and "float" in offenders[0]
