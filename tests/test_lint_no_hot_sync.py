"""Lint gate: no raw blocking host↔device syncs in the training hot
path (ISSUE 4, the training twin of test_lint_no_bare_except.py).

The tentpole made steady-state training dispatch-free-running: every
device→host fetch in the step loop goes through
``StepSyncLedger.resolve()`` so it is counted, timed, and visible on
``/metrics`` as ``train_sync_*``.  A raw ``float(...)`` /
``np.asarray(...)`` / ``jax.device_get(...)`` / ``.block_until_ready()``
re-introduced into the step-loop bodies would silently bring back the
one-RTT-per-step serialization PR 4 removed — this AST walk keeps it
out.

Scope: the functions that ARE the step loop — ``train_loop`` in
runtime/harness.py and the train-step path in parallel/trainer.py
(``train_step`` / ``train_steps`` / the compiled bodies).  A forbidden
call is exempt only when its own arguments contain a ``.resolve(...)``
call (``float(ledger.resolve(...))`` — already host-side by
construction).  Measurement helpers (benchmark/_slope_time/hard_sync)
and eval are off the steady-state path and stay unlinted.
"""

import ast
import pathlib

import tf_operator_tpu

PKG_ROOT = pathlib.Path(tf_operator_tpu.__file__).parent

#: (file, function names that constitute its step-loop hot path)
HOT_FUNCTIONS = {
    "runtime/harness.py": {"train_loop"},
    "parallel/trainer.py": {
        "train_step",
        "train_steps",
        "_step_body",
        "_build_step",
        "_build_multi_step",
    },
}

#: bare-name calls that force a device→host sync
FORBIDDEN_NAMES = {"float"}
#: attribute calls that force one (any receiver: np.asarray,
#: jax.device_get, arr.block_until_ready)
FORBIDDEN_ATTRS = {"asarray", "device_get", "block_until_ready"}


def _forbidden(call: ast.Call):
    f = call.func
    if isinstance(f, ast.Name) and f.id in FORBIDDEN_NAMES:
        return f.id
    if isinstance(f, ast.Attribute) and f.attr in FORBIDDEN_ATTRS:
        return f.attr
    return None


def _contains_resolve(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Attribute)
        and n.func.attr == "resolve"
        for n in ast.walk(node)
    )


def _is_exempt(call: ast.Call) -> bool:
    args = list(call.args) + [kw.value for kw in call.keywords]
    return any(_contains_resolve(a) for a in args)


def find_hot_syncs(tree: ast.AST, func_names, label: str):
    offenders = []
    for fn in ast.walk(tree):
        if (
            isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and fn.name in func_names
        ):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = _forbidden(node)
                    if name is not None and not _is_exempt(node):
                        offenders.append(f"{label}:{node.lineno} {name}(...)")
    return offenders


def _lint_package():
    offenders = []
    for rel, funcs in sorted(HOT_FUNCTIONS.items()):
        path = PKG_ROOT / rel
        tree = ast.parse(path.read_text(), filename=str(path))
        offenders.extend(find_hot_syncs(tree, funcs, rel))
    return offenders


def test_no_raw_syncs_in_training_hot_path():
    offenders = _lint_package()
    assert not offenders, (
        "raw blocking host<->device syncs in the training step loop — "
        "route them through StepSyncLedger.resolve() "
        "(utils/metrics.py):\n  " + "\n  ".join(offenders)
    )


def test_walker_catches_planted_syncs():
    """The gate itself works: each forbidden spelling is found inside a
    hot function, resolve-routed fetches are not, and functions outside
    the hot set are ignored."""

    src = (
        "def train_loop(trainer, batch):\n"
        "    for step in range(10):\n"
        "        m = trainer.train_step(batch)\n"
        "        a = float(m['loss'])\n"                 # offender
        "        b = np.asarray(m['loss'])\n"            # offender
        "        jax.device_get(m)\n"                    # offender
        "        m['loss'].block_until_ready()\n"        # offender
        "        ok = float(ledger.resolve('step', m['loss']))\n"  # exempt
        "        ok2 = np.asarray(ledger.resolve('w', m))\n"       # exempt
        "\n"
        "def evaluate(batches):\n"
        "    return [float(b) for b in batches]\n"       # not hot: ignored
    )
    offenders = find_hot_syncs(ast.parse(src), {"train_loop"}, "planted")
    assert [o.split()[1] for o in offenders] == [
        "float(...)", "asarray(...)", "device_get(...)",
        "block_until_ready(...)",
    ]


def test_resolve_argument_interior_is_not_exempt():
    """``ledger.resolve('x', float(y))`` evaluates float(y) BEFORE the
    ledger sees anything — that interior sync must still be flagged."""

    src = (
        "def train_step(self, batch):\n"
        "    self.ledger.resolve('x', float(batch['y']))\n"
    )
    offenders = find_hot_syncs(ast.parse(src), {"train_step"}, "planted")
    assert len(offenders) == 1 and "float" in offenders[0]
