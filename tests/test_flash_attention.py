"""Flash-attention kernel numerics vs the XLA reference.

CPU runs exercise the kernel through the pallas interpreter (bit-exact
algorithm, no TPU needed); RUN_TPU_TESTS=1 additionally runs the
compiled kernel on the real chip.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (pallas interpret-mode kernels); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.ops import dot_product_attention, flash_attention
from tf_operator_tpu.ops.flash_attention import attention

ON_TPU = jax.default_backend() == "tpu"
INTERPRET = not ON_TPU
# the MXU's default f32 matmul precision is ~1e-3; the interpreter is exact
TOL = dict(atol=5e-3, rtol=5e-3) if ON_TPU else dict(atol=2e-5, rtol=2e-5)


def rand_qkv(rng, b, h, s, d, dtype=jnp.float32, sk=None):
    r = np.random.RandomState(rng)
    shape_q = (b, h, s, d)
    shape_k = (b, h, sk or s, d)
    q = jnp.asarray(r.normal(size=shape_q), dtype)
    k = jnp.asarray(r.normal(size=shape_k), dtype)
    v = jnp.asarray(r.normal(size=shape_k), dtype)
    return q, k, v


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("s", [128, 256])
    def test_matches_reference(self, causal, s):
        q, k, v = rand_qkv(0, 2, 3, s, 64)
        got = flash_attention(q, k, v, causal, 128, 128, INTERPRET)
        want = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(got, want, **TOL)

    def test_uneven_block_sizes(self):
        q, k, v = rand_qkv(1, 1, 2, 256, 64)
        got = flash_attention(q, k, v, True, 64, 128, INTERPRET)
        want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(got, want, **TOL)

    def test_cross_attention_lengths(self):
        q, k, v = rand_qkv(2, 1, 2, 128, 64, sk=256)
        got = flash_attention(q, k, v, False, 128, 128, INTERPRET)
        want = dot_product_attention(q, k, v, causal=False)
        np.testing.assert_allclose(got, want, **TOL)

    def test_bfloat16(self):
        q, k, v = rand_qkv(3, 1, 2, 128, 64, dtype=jnp.bfloat16)
        got = flash_attention(q, k, v, True, 128, 128, INTERPRET)
        want = dot_product_attention(q, k, v, causal=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            got.astype(np.float32), want.astype(np.float32), atol=2e-2, rtol=2e-2
        )


class TestFlashGrad:
    """The VJP is backed by the pallas backward kernels (dq + dk/dv with
    in-kernel softmax recompute from the saved logsumexp); every case
    checks dq, dk, dv against the XLA backward."""

    def _check(self, q, k, v, causal, block_q=128, block_k=128, tol=None):
        # weighted sum => non-trivial dO, unlike .sum() whose dO is ones
        w = jnp.asarray(
            np.random.RandomState(99).normal(size=q.shape), jnp.float32
        )

        def f_flash(q, k, v):
            return (
                flash_attention(q, k, v, causal, block_q, block_k, INTERPRET)
                .astype(jnp.float32) * w
            ).sum()

        def f_ref(q, k, v):
            return (
                dot_product_attention(q, k, v, causal=causal).astype(jnp.float32)
                * w
            ).sum()

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(a, np.float32),
                np.asarray(b, np.float32),
                err_msg=name,
                **(tol or TOL),
            )

    @pytest.mark.parametrize("causal", [False, True])
    @pytest.mark.parametrize("s", [128, 256])
    def test_vjp_matches_reference(self, causal, s):
        q, k, v = rand_qkv(4, 1, 2, s, 64)
        self._check(q, k, v, causal)

    def test_uneven_blocks(self):
        q, k, v = rand_qkv(11, 1, 2, 256, 64)
        self._check(q, k, v, True, block_q=64, block_k=128)

    def test_large_blocks(self):
        """256x256 — the llama_sweep autotune matrix's candidate shapes
        must be numerically identical to the default 128x128."""
        q, k, v = rand_qkv(13, 1, 2, 512, 64)
        self._check(q, k, v, True, block_q=256, block_k=256)

    def test_cross_attention_lengths(self):
        q, k, v = rand_qkv(12, 1, 2, 128, 64, sk=256)
        self._check(q, k, v, False)

    def test_bfloat16_grads(self):
        q, k, v = rand_qkv(13, 1, 2, 128, 64, dtype=jnp.bfloat16)
        self._check(q, k, v, True, tol=dict(atol=3e-2, rtol=3e-2))

    def test_xla_recompute_fallback_env(self, monkeypatch):
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BWD", "0")
        q, k, v = rand_qkv(14, 1, 2, 128, 64)
        self._check(q, k, v, True)


class TestDispatch:
    def test_falls_back_off_tpu_or_with_mask(self):
        q, k, v = rand_qkv(5, 1, 1, 128, 64)
        mask = jnp.ones((1, 1, 128, 128), bool)
        out = attention(q, k, v, causal=False, mask=mask)
        want = dot_product_attention(q, k, v, mask=mask)
        np.testing.assert_allclose(out, want, atol=1e-6)

    def test_unaligned_seq_falls_back(self):
        q, k, v = rand_qkv(6, 1, 1, 100, 64)
        out = attention(q, k, v, causal=True)
        want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(out, want, atol=1e-6)

    def test_env_kill_switch(self, monkeypatch):
        import importlib

        # the package re-exports the function under the module's name,
        # so resolve the module explicitly
        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")

        monkeypatch.setenv("TPU_OPERATOR_FLASH", "0")
        q, k, v = rand_qkv(7, 1, 1, 128, 64)
        assert not fa._flash_applicable(q, k, None, None, 128, 128)


class TestShardedFlash:
    def test_shard_map_over_dp_tp_matches_reference(self):
        """pallas_call has no GSPMD rule; the dispatcher's shard_map
        wrapper must produce exact per-shard results on a dp×tp mesh."""

        from tf_operator_tpu.ops.flash_attention import flash_attention_sharded
        from tf_operator_tpu.parallel import make_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices (virtual CPU mesh)")
        mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
        q, k, v = rand_qkv(9, 4, 4, 128, 64)
        got = flash_attention_sharded(
            q, k, v, mesh, causal=True, interpret=INTERPRET
        )
        want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(got, want, **TOL)

    def test_mesh_applicability(self):
        from tf_operator_tpu.ops.flash_attention import _mesh_flash_applicable
        from tf_operator_tpu.parallel import make_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        q, k, _ = rand_qkv(10, 4, 4, 128, 64)
        # mesh=None in a MULTI-device program: inputs may carry GSPMD
        # shardings pallas_call can't partition — XLA fallback (round-1
        # advisor fix); "single" only when the program has one device
        assert _mesh_flash_applicable(None, q, k) is None
        import unittest.mock as mock

        with mock.patch.object(jax, "device_count", return_value=1):
            assert _mesh_flash_applicable(None, q, k) == "single"
        dp4 = make_mesh({"dp": 4}, devices=jax.devices()[:4])
        assert _mesh_flash_applicable(dp4, q, k) == "sharded"
        # sp-sharded meshes belong to ring attention, not this kernel
        assert _mesh_flash_applicable(make_mesh({"sp": 4}, devices=jax.devices()[:4]), q, k) is None
        # indivisible batch/heads fall back
        q3 = q[:3]
        assert _mesh_flash_applicable(dp4, q3, k) is None


@pytest.mark.skipif(
    not (ON_TPU and os.environ.get("RUN_TPU_TESTS") == "1"),
    reason="compiled-kernel check needs the real chip (RUN_TPU_TESTS=1)",
)
class TestFlashOnChip:
    def test_compiled_matches_reference(self):
        q, k, v = rand_qkv(8, 2, 4, 512, 128, dtype=jnp.bfloat16)
        got = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))(q, k, v)
        want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            got.astype(np.float32), want.astype(np.float32), atol=2e-2, rtol=2e-2
        )


class TestFlashGQA:
    """Kernel-native GQA: K/V at Hkv width, no repeat materialised —
    forward via index-mapped BlockSpecs, dk/dv via the grouped kv-major
    grid (every query head in a group accumulates into one scratch)."""

    def _qkv(self, B=2, H=4, HKV=2, S=64, D=32, seed=41):
        r = np.random.RandomState(seed)
        mk = lambda h, s: jnp.asarray(r.randn(B, h, S, D), jnp.float32) * s
        return mk(H, 0.3), mk(HKV, 0.3), mk(HKV, 1.0)

    @pytest.mark.parametrize("causal", [False, True])
    def test_fwd_and_grads_match_grouped_reference(self, causal):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv()

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal, 16, 16, True) ** 2).mean()

        def loss_ref(q, k, v):
            # reference expands explicitly; autodiff of the repeat is
            # the group-sum, so native-width grads come out directly
            kf, vf = (jnp.repeat(a, 2, axis=1) for a in (k, v))
            return (dot_product_attention(q, kf, vf, causal=causal) ** 2).mean()

        out = flash_attention(q, k, v, causal, 16, 16, True)
        ref = dot_product_attention(
            q, jnp.repeat(k, 2, 1), jnp.repeat(v, 2, 1), causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5, err_msg=name
            )

    def test_dot_product_gqa_matches_expanded(self):
        q, k, v = self._qkv(seed=42)
        a = dot_product_attention(q, k, v, causal=True)
        b = dot_product_attention(
            q, jnp.repeat(k, 2, 1), jnp.repeat(v, 2, 1), causal=True
        )
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    def test_indivisible_heads_rejected(self):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(H=4, HKV=3)
        with pytest.raises(ValueError, match="multiple"):
            flash_attention(q, k, v, False, 16, 16, True)


class TestSlidingWindow:
    """Mistral-style local attention: banded mask in the reference,
    block-skipped in the kernels, consistent in decode."""

    def _qkv(self, B=2, H=2, S=128, D=32, seed=51):
        r = np.random.RandomState(seed)
        mk = lambda s: jnp.asarray(r.randn(B, H, S, D), jnp.float32) * s
        return mk(0.3), mk(0.3), mk(1.0)

    @staticmethod
    def _banded_ref(q, k, v, w):
        s = q.shape[-2]
        qpos = jnp.arange(s)[:, None]
        kpos = jnp.arange(s)[None, :]
        mask = (qpos >= kpos) & (qpos - kpos < w)
        return dot_product_attention(q, k, v, mask=mask[None, None])

    @pytest.mark.parametrize("w", [1, 16, 40, 128])
    def test_reference_matches_banded_mask(self, w):
        q, k, v = self._qkv()
        out = dot_product_attention(q, k, v, causal=True, window=w)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._banded_ref(q, k, v, w)),
            atol=1e-6,
        )

    @pytest.mark.parametrize("w", [16, 40, 128])
    def test_flash_fwd_and_grads_match_reference(self, w):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv()

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, 16, 16, True, window=w) ** 2).mean()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True, window=w) ** 2).mean()

        out = flash_attention(q, k, v, True, 16, 16, True, window=w)
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5, err_msg=name
            )

    def test_window_with_gqa(self):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        r = np.random.RandomState(52)
        q = jnp.asarray(r.randn(2, 4, 64, 32), jnp.float32) * 0.3
        k = jnp.asarray(r.randn(2, 2, 64, 32), jnp.float32) * 0.3
        v = jnp.asarray(r.randn(2, 2, 64, 32), jnp.float32)
        out = flash_attention(q, k, v, True, 16, 16, True, window=24)
        ref = dot_product_attention(q, k, v, causal=True, window=24)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def test_window_requires_causal(self):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(S=32)
        with pytest.raises(ValueError, match="causal"):
            dot_product_attention(q, k, v, window=8)
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, False, 16, 16, True, window=8)

    def test_window_below_one_rejected(self):
        q, k, v = self._qkv(S=32)
        with pytest.raises(ValueError, match=">= 1"):
            dot_product_attention(q, k, v, causal=True, window=0)
        from tf_operator_tpu.ops.flash_attention import flash_attention

        with pytest.raises(ValueError, match=">= 1"):
            flash_attention(q, k, v, True, 16, 16, True, window=0)

    @pytest.mark.parametrize("bq,bk", [(16, 32), (32, 16), (16, 16)])
    @pytest.mark.parametrize("w", [16, 40])
    def test_banded_grids_unequal_blocks(self, bq, bk, w):
        """Band width/remap math must hold for block_q != block_k."""

        from tf_operator_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(S=128)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, True, bq, bk, True, window=w) ** 2).mean()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True, window=w) ** 2).mean()

        out = flash_attention(q, k, v, True, bq, bk, True, window=w)
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5, err_msg=name
            )

    def test_band_width_tight_when_aligned(self):
        from tf_operator_tpu.ops.flash_attention import _kv_band_width, _q_band_width

        # block_q == block_k == window: exactly the diagonal + previous
        assert _kv_band_width(128, 128, 128, 64) == 2
        assert _q_band_width(128, 128, 128, 64) == 2
        # w=1: diagonal only
        assert _kv_band_width(128, 128, 1, 64) == 1
        # misaligned blocks get the +1 slack
        assert _kv_band_width(16, 32, 16, 64) == 3

    def test_window_cross_lengths_rejected(self):
        from tf_operator_tpu.ops.flash_attention import flash_attention

        r = np.random.RandomState(60)
        q = jnp.asarray(r.randn(1, 2, 64, 32), jnp.float32)
        k = jnp.asarray(r.randn(1, 2, 32, 32), jnp.float32)
        with pytest.raises(ValueError, match="Sq == Sk"):
            flash_attention(q, k, k, True, 16, 16, True, window=16)


class TestWindowProperty:
    """Property sweep: any legal (seq, window, block) combination must
    match the banded reference, forward and gradients."""

    def test_random_configs(self):
        pytest.importorskip("hypothesis")
        from hypothesis import given, settings, strategies as st

        from tf_operator_tpu.ops.flash_attention import flash_attention

        @settings(max_examples=20, deadline=None)
        @given(
            s_blocks=st.integers(2, 8),
            bq=st.sampled_from([8, 16, 32]),
            bk=st.sampled_from([8, 16, 32]),
            w=st.integers(1, 96),
            seed=st.integers(0, 2**16),
        )
        def run(s_blocks, bq, bk, w, seed):
            import math

            s = s_blocks * (bq * bk // math.gcd(bq, bk))
            r = np.random.RandomState(seed)
            q = jnp.asarray(r.randn(1, 2, s, 16), jnp.float32) * 0.3
            k = jnp.asarray(r.randn(1, 2, s, 16), jnp.float32) * 0.3
            v = jnp.asarray(r.randn(1, 2, s, 16), jnp.float32)
            out = flash_attention(q, k, v, True, bq, bk, True, window=w)
            ref = dot_product_attention(q, k, v, causal=True, window=w)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5,
                err_msg=f"s={s} bq={bq} bk={bk} w={w}",
            )
            gf = jax.grad(
                lambda a, b, c: (
                    flash_attention(a, b, c, True, bq, bk, True, window=w) ** 2
                ).mean(),
                argnums=(0, 1, 2),
            )(q, k, v)
            gr = jax.grad(
                lambda a, b, c: (
                    dot_product_attention(a, b, c, causal=True, window=w) ** 2
                ).mean(),
                argnums=(0, 1, 2),
            )(q, k, v)
            for name, a, b in zip("dq dk dv".split(), gf, gr):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), atol=3e-5, rtol=3e-5,
                    err_msg=f"{name} s={s} bq={bq} bk={bk} w={w}",
                )

        run()


class TestDefaultBlockEnv:
    def test_env_overrides(self, monkeypatch):
        from tf_operator_tpu.ops.flash_attention import default_flash_blocks

        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_K", raising=False)
        # r5 default: the autotune winner at every measured shape —
        # and the VMEM ceiling (see default_flash_blocks)
        assert default_flash_blocks() == (1024, 1024)
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BLOCK_Q", "128")
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BLOCK_K", "512")
        assert default_flash_blocks() == (128, 512)

    def test_head_dim_caps_default_block_class(self, monkeypatch):
        """ADVICE r5 #1: the 1024-class default was measured at the
        16 MB scoped-VMEM ceiling only for D=64/128 — a larger head dim
        must cap the BUILT-IN default at the 512 class (block footprint
        scales with D) instead of routing into a Pallas compile OOM.
        Pins (caller args, BLOCK env) stay exactly as given."""

        from tf_operator_tpu.ops.flash_attention import resolve_flash_blocks

        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_K", raising=False)
        # measured head dims keep the 1024 default
        assert resolve_flash_blocks(None, None, 2048, 2048, head_dim=64) == (1024, 1024)
        assert resolve_flash_blocks(None, None, 2048, 2048, head_dim=128) == (1024, 1024)
        # D > 128: capped to the 512 class before sequence tiling
        assert resolve_flash_blocks(None, None, 2048, 2048, head_dim=256) == (512, 512)
        # the cap composes with sequence shrinking (512 doesn't tile 256)
        assert resolve_flash_blocks(None, None, 256, 256, head_dim=256) == (256, 256)
        # head_dim unknown (legacy callers): old behavior
        assert resolve_flash_blocks(None, None, 2048, 2048) == (1024, 1024)
        # caller pins are NEVER adjusted, big D or not
        assert resolve_flash_blocks(1024, None, 2048, 2048, head_dim=256) == (1024, 512)
        # env pins are NEVER adjusted either (a sweep measures what it set)
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BLOCK_Q", "1024")
        assert resolve_flash_blocks(None, None, 2048, 2048, head_dim=256) == (1024, 512)

    def test_attention_routes_big_head_dim_to_capped_blocks(self, monkeypatch):
        """The dispatching attention() passes q's head dim through, so
        a D=256 model auto-resolves 512-class blocks (the regression
        route: head_dim>128 through the default path)."""

        import importlib

        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_K", raising=False)
        seen = {}
        real = fa._flash_applicable

        def spy(q, k, bias, mask, block_q, block_k, window=None):
            seen["blocks"] = (block_q, block_k)
            return real(q, k, bias, mask, block_q, block_k, window)

        monkeypatch.setattr(fa, "_flash_applicable", spy)
        q, k, v = rand_qkv(11, 1, 2, 2048, 256)
        fa.attention(q, k, v, causal=True)
        assert seen["blocks"] == (512, 512)

    def test_attention_uses_env_blocks(self, monkeypatch):
        """attention() resolves None block args from the env — the
        sweep's per-variant processes tune the kernel without touching
        model code.  On CPU the dispatcher falls back to XLA either
        way; this pins the resolution logic, not the kernel."""
        import importlib

        # the package re-exports flash_attention the FUNCTION over the
        # submodule name — resolve the module explicitly
        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")

        seen = {}
        real = fa._flash_applicable

        def spy(q, k, bias, mask, block_q, block_k, window=None):
            seen["blocks"] = (block_q, block_k)
            return real(q, k, bias, mask, block_q, block_k, window)

        monkeypatch.setattr(fa, "_flash_applicable", spy)
        monkeypatch.setenv("TPU_OPERATOR_FLASH_BLOCK_Q", "128")
        q, k, v = rand_qkv(7, 1, 2, 256, 64)
        fa.attention(q, k, v, causal=True)
        # BLOCK_Q pinned by env, BLOCK_K from the 256 default
        assert seen["blocks"] == (128, 256)

    def test_block_keyed_crossover(self, monkeypatch):
        """The auto-crossover floor is keyed to the blocks in use
        (each tier's floor = shortest seq where those blocks measured
        a win/tie vs XLA, r5 wide-xover sweeps): 512-class blocks win
        from seq 512; the 256-class floor is head-dim split (wins from
        256 at D >= 128, from 1024 at D = 64 where XLA takes short
        seqs — wx6 calibration); 128x128 from 2048.  Shapes whose
        defaults shrank (seq 1152 tiles only 128) keep the 128-block
        floor; force bypasses the floor entirely."""

        import importlib

        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")
        monkeypatch.setattr(fa.jax, "default_backend", lambda: "tpu")
        monkeypatch.delenv("TPU_OPERATOR_FLASH", raising=False)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_MIN_SEQ", raising=False)

        def applicable(seq, bq, bk, d=64):
            q, k, _ = rand_qkv(9, 1, 2, seq, d)
            return fa._flash_applicable(q, k, None, None, bq, bk)

        assert applicable(512, 512, 512)        # 512 blocks: floor 512
        assert not applicable(512, 256, 256)    # 256@D64: floor 1024
        assert applicable(1024, 256, 256)
        # 256-class floor is head-dim split: D>=128 wins from 256
        assert applicable(256, 256, 256, d=128)
        assert not applicable(256, 256, 256, d=64)
        assert not applicable(1152, 128, 128)   # 128 blocks: floor 2048
        assert applicable(2048, 128, 128)
        # a single shrunken dim keys the floor on the SMALLER class
        assert not applicable(512, 512, 256)
        # env floor override wins over the block-derived floor
        monkeypatch.setenv("TPU_OPERATOR_FLASH_MIN_SEQ", "2048")
        assert not applicable(1024, 512, 512)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_MIN_SEQ")
        # force bypasses the floor but not tiling
        monkeypatch.setenv("TPU_OPERATOR_FLASH", "1")
        assert applicable(1152, 128, 128)
        assert not applicable(1152, 256, 256)   # 1152 % 256 != 0

    def test_attention_resolves_shrunken_blocks(self, monkeypatch):
        """attention() shrinks unpinned default dims until they tile
        (seq 1152: 1024→512→256→128) and hands the RESOLVED blocks to the
        dispatcher, so the crossover sees what will actually run."""

        import importlib

        fa = importlib.import_module("tf_operator_tpu.ops.flash_attention")
        seen = {}
        real = fa._flash_applicable

        def spy(q, k, bias, mask, block_q, block_k, window=None):
            seen["blocks"] = (block_q, block_k)
            return real(q, k, bias, mask, block_q, block_k, window)

        monkeypatch.setattr(fa, "_flash_applicable", spy)
        monkeypatch.setenv("TPU_OPERATOR_FLASH", "1")
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_Q", raising=False)
        monkeypatch.delenv("TPU_OPERATOR_FLASH_BLOCK_K", raising=False)
        q, k, v = rand_qkv(9, 1, 2, 1152, 64)
        fa.attention(q, k, v, causal=True)
        assert seen["blocks"] == (128, 128)
