"""Contention chaos soak (ISSUE 16 acceptance): the fleet scheduler
under mixed-priority load, shrinking-then-returning capacity, and PR 1
apiserver fault injection — nothing wedges.

The scenario, over the production-shaped path (controller → retrying
HTTP clients → kubesim MiniApiServer with a FaultInjector throwing
5xx/429/resets at every route):

1. Two low-priority bulk trainers admit and fill the 24-chip pool,
   stamping fresh async-checkpoint ages as they run.
2. A critical and a high gang arrive into a full pool: the scheduler
   preempts across jobs — checkpoint-gated shed-to-smaller-world or
   whole-gang revoke — until both bursts run.  Victims park VISIBLY
   (Queued condition, queue gauges), not as dead pods.
3. Capacity shrinks under everyone's feet: kubesim revokes through the
   scheduler's victim choice (lowest class first, never LIFO), and the
   synchronous ``note_revoked`` park means no victim is ever misread
   as a replica failure.
4. Capacity returns: every parked gang re-admits by priority × age,
   resumes from its checkpoint, and runs to completion.

Pinned acceptance: 4 jobs × 3 priority classes all end Succeeded, at
least one cross-job preemption whose victim carries
Preempted → Resumed(ResumedFromCheckpoint) → Succeeded, monotone
per-job decision sequences (zero flapping), bounded sync count (no hot
requeue loops), and non-zero injected faults.  The decision counts are
published into SUITE_RECORD via record_suite_extra so a silently
wedged soak reddens benchmarks/check_tier_budget.py.
"""

import random
import sys
import time

import pytest

pytestmark = pytest.mark.slow

from tests.conftest import record_suite_extra
from tests.testutil import new_job
from tf_operator_tpu.api.types import (
    JobConditionType,
    PodPhase,
    SchedulingSpec,
)
from tf_operator_tpu.backend.kube import KubeBackend
from tf_operator_tpu.backend.kubejobs import KubeEventRecorder, KubeJobStore
from tf_operator_tpu.backend.kubesim import MiniApiServer
from tf_operator_tpu.backend.retry import RetryPolicy
from tf_operator_tpu.controller.controller import TPUJobController
from tf_operator_tpu.controller.reconciler import ReconcilerConfig
from tf_operator_tpu.controller.scheduler import Scheduler
from tf_operator_tpu.utils.metrics import Metrics

POOL = 24  # three v5e-8 slices
BULK_SLEEP = [sys.executable, "-c", "import time; time.sleep(5.0)"]
BURST_SLEEP = [sys.executable, "-c", "import time; time.sleep(1.2)"]

#: decision-sequence automaton: every per-job action must extend the
#: previous one along these edges — anything else is flapping (an
#: admit/queue oscillation) or a phantom transition (shed of a parked
#: gang).  ``queue`` appears at most once per job by construction (the
#: scheduler dedups queue decisions), revoke must come from a held
#: grant, and a revoked gang either re-admits directly or waits
#: visibly (one queue decision) before re-admitting.
MONOTONE = {
    None: {"queue", "admit"},
    "queue": {"admit"},
    "admit": {"shed", "revoke"},
    "shed": {"shed", "revoke"},
    "revoke": {"admit", "queue"},
}


def fast_policy(seed, **kw):
    kw.setdefault("max_attempts", 6)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.2)
    kw.setdefault("deadline", 5.0)
    return RetryPolicy(rng=random.Random(seed), **kw)


class CapacityBackend(KubeBackend):
    """KubeBackend + the ``total_chips`` probe the scheduler's capacity
    callable expects (kubesim owns the pool server-side; same process,
    so read it directly — the control traffic still rides faulty HTTP)."""

    def __init__(self, sim, **kw):
        self._sim = sim
        super().__init__(sim.url, **kw)

    @property
    def total_chips(self):
        return self._sim.total_chips


class SoakRig:
    def __init__(self):
        self.sim = MiniApiServer(total_chips=POOL, fault_seed=77).start()
        # ~10% combined fault probability on ALL routes — the PR 1
        # injector: 503+Retry-After, naked 429s, connection resets
        self.sim.faults.add(mode="error", status=503, retry_after=0.02,
                            probability=0.04)
        self.sim.faults.add(mode="error", status=429, probability=0.03)
        self.sim.faults.add(mode="reset", probability=0.03)

        self.metrics = Metrics()
        self.sched = Scheduler(
            metrics=self.metrics, preemption_cooldown_seconds=0.3
        )
        self.store = KubeJobStore(
            self.sim.url, retry=fast_policy(1), metrics=self.metrics
        )
        self.backend = CapacityBackend(
            self.sim, retry=fast_policy(2), metrics=self.metrics
        )
        self.recorder = KubeEventRecorder(self.sim.url, metrics=self.metrics)
        self.controller = TPUJobController(
            self.store, self.backend,
            config=ReconcilerConfig(resolver=self.backend.resolver),
            metrics=self.metrics, recorder=self.recorder,
            scheduler=self.sched,
            resync_period=0.3, expectations_timeout=0.3,
        )
        # capacity-shrink revocation routes through the scheduler's
        # victim choice + synchronous park (satellite 1)
        self.sim.scheduler = self.sched
        self.sweeps = 0
        self.controller.run(threadiness=2)

    def add_job(self, name, prio, slices, command):
        j = new_job(
            name=name, tpu_slice=slices, tpu_topology="v5e-8",
            command=command,
        )
        j.spec.scheduling = SchedulingSpec(priority_class=prio)
        self.store.create(j)

    def stamp_checkpoints(self, names):
        """The trainers' async-checkpoint heartbeat: a fresh durability
        stamp per tick, which is what opens the elective-preemption
        checkpoint gate for these victims."""

        now = time.time()
        for name in names:
            self.metrics.set(
                "checkpoint_last_success_unix", now, job=f"default/{name}"
            )

    def pump(self, until, timeout, what, checkpoint=()):
        deadline = time.time() + timeout
        while time.time() < deadline:
            self.stamp_checkpoints(checkpoint)
            self.sched.evaluate_once()
            self.sweeps += 1
            if until():
                return
            time.sleep(0.05)
        raise TimeoutError(what)

    def running_pods(self, name):
        return sum(
            1
            for p in self.backend.list_pods(
                "default", {"tpujob.dist/job-name": name}
            )
            if p.phase is PodPhase.RUNNING
        )

    def status(self, name):
        job = self.store.get("default", name)
        return None if job is None else job.status

    def succeeded(self, name):
        st = self.status(name)
        return st is not None and st.has_condition(JobConditionType.SUCCEEDED)

    def decision_actions(self, name):
        """Oldest-first action sequence for one job from the decision
        log (the same log GET /scheduler serves)."""

        newest_first = self.sched.snapshot()["decisions"]
        return [
            d["action"]
            for d in reversed(newest_first)
            if d["job"] == f"default/{name}"
        ]

    def stop(self):
        self.controller.stop()
        self.recorder.close()
        self.backend.close()
        self.store.close()
        self.sim.stop()


class TestContentionSoak:
    def test_mixed_priority_contention_shrink_and_return(self):
        rig = SoakRig()
        t0 = time.time()
        try:
            self._run(rig)
        finally:
            rig.stop()
        # no hot requeue loop: syncs stay proportional to the soak's
        # wall clock (a wedged job hot-loops hundreds of syncs/second)
        elapsed = time.time() - t0
        syncs = rig.metrics.total("tpujob_syncs_total")
        assert syncs < 40.0 * elapsed + 400.0, (
            f"sync storm: {syncs:.0f} syncs in {elapsed:.1f}s"
        )

    def _run(self, rig):
        bulks = ("bulk-a", "bulk-b")
        jobs = ("bulk-a", "bulk-b", "burst-crit", "burst-hi")

        # ---- phase 1: bulk load fills the pool ---------------------
        rig.add_job("bulk-a", "low", slices=2, command=BULK_SLEEP)
        rig.add_job("bulk-b", "low", slices=1, command=BULK_SLEEP)
        rig.pump(
            lambda: all(rig.running_pods(n) > 0 for n in bulks),
            timeout=20.0, what="bulk jobs running", checkpoint=bulks,
        )
        snap = rig.sched.snapshot()
        assert {e["job"] for e in snap["admitted"]} == {
            "default/bulk-a", "default/bulk-b",
        }

        # ---- phase 2: burst arrivals into a full pool --------------
        # critical + high arrive; the pool is full, so BOTH admissions
        # require cross-job preemption of the (checkpoint-fresh) lows
        rig.add_job("burst-crit", "critical", slices=1, command=BURST_SLEEP)
        rig.add_job("burst-hi", "high", slices=1, command=BURST_SLEEP)

        def bursts_admitted():
            admitted = {
                e["job"] for e in rig.sched.snapshot()["admitted"]
            }
            return {"default/burst-crit", "default/burst-hi"} <= admitted

        rig.pump(
            bursts_admitted, timeout=20.0,
            what="bursts admitted via preemption", checkpoint=bulks,
        )
        assert rig.metrics.total("scheduler_preemptions_total") >= 1.0
        # the parked victims are VISIBLE, not dead: Queued condition or
        # shed marker, never Failed
        for name in bulks:
            st = rig.status(name)
            assert not st.has_condition(JobConditionType.FAILED), name

        # ---- phase 3: capacity shrinks under everyone --------------
        revoked = rig.sim.set_total_chips(8)
        assert revoked, "shrink to 8 chips must revoke someone"
        # victim choice went through the scheduler: the critical gang
        # survives a shrink that still fits it (never LIFO)
        assert "burst-crit" not in revoked

        def victims_parked():
            for name in revoked:
                st = rig.status(name)
                if st is None or st.has_condition(JobConditionType.FAILED):
                    return False
                done = st.has_condition(JobConditionType.SUCCEEDED)
                queued = any(
                    c.type is JobConditionType.QUEUED and c.status
                    for c in st.conditions
                )
                if not (done or queued):
                    return False
            return True

        rig.pump(
            victims_parked, timeout=20.0,
            what="shrink victims visibly parked", checkpoint=bulks,
        )

        # ---- phase 4: capacity returns — everyone completes --------
        rig.sim.set_total_chips(POOL)
        rig.pump(
            lambda: all(rig.succeeded(n) for n in jobs),
            timeout=40.0, what="all jobs Succeeded after capacity return",
            checkpoint=bulks,
        )

        # ---- the pinned contract -----------------------------------
        admitted_total = int(rig.metrics.counter("scheduler_admitted_total"))
        preempt_total = int(rig.metrics.total("scheduler_preemptions_total"))
        record_suite_extra("schedulerSoak", {
            "admitted": admitted_total,
            "preemptions": preempt_total,
            "sweeps": rig.sweeps,
        })
        assert admitted_total >= 4
        assert preempt_total >= 1

        # at least one cross-job preemption victim resumed from its
        # checkpoint and ran to completion
        resumed_and_done = []
        for name in jobs:
            st = rig.status(name)
            assert st.has_condition(JobConditionType.SUCCEEDED), (
                f"{name} did not finish: "
                f"{[(c.type.value, c.status, c.reason) for c in st.conditions]}"
            )
            preempted = any(
                c.type is JobConditionType.PREEMPTED for c in st.conditions
            )
            resumed = any(
                c.type is JobConditionType.RESUMED
                and c.reason == "ResumedFromCheckpoint"
                for c in st.conditions
            )
            if preempted and resumed:
                resumed_and_done.append(name)
        assert resumed_and_done, "no victim resumed from checkpoint"

        # monotone per-job decision sequences: zero flapping
        for name in jobs:
            seq = rig.decision_actions(name)
            assert seq, f"{name} has no decisions"
            prev = None
            for action in seq:
                assert action in MONOTONE[prev], (
                    f"{name}: {prev} -> {action} flap in {seq}"
                )
                prev = action

        # the faults actually fired and the clients actually retried
        assert rig.sim.faults.total_injected() > 0
        assert rig.metrics.total("api_client_retries_total") > 0
