"""KV-cache generation: cached decode must equal full-recompute greedy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# default-tier exclusion (generation-loop compiles); see README 'Tests run in two tiers'
pytestmark = pytest.mark.slow

from tf_operator_tpu.models import generate, gpt_tiny, llama_tiny

VOCAB = 128

import sys as _sys, os as _os
_sys.path.insert(0, _os.path.dirname(__file__))
from testutil import assert_decode_equiv_up_to_ties  # noqa: E402



def _reference_greedy(model, params, prompt, n):
    """No-cache reference: rerun the full forward on the growing
    sequence each step and argmax the last position."""

    ids = prompt
    for _ in range(n):
        logits = model.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


@pytest.mark.parametrize("family", ["gpt", "llama", "moe"])
def test_cached_greedy_matches_full_recompute(family):
    # moe_tiny's defaults (cf=2.0, E=4) make the training capacity
    # s-dropless, so the full-context reference routes identically to
    # the dropless cached-decode path and parity is exact
    from tf_operator_tpu.models import moe_tiny

    make = {"gpt": gpt_tiny, "llama": llama_tiny, "moe": moe_tiny}[family]
    model = make(vocab_size=VOCAB, max_len=64)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=8)
    ref = _reference_greedy(model, params, prompt, 8)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_is_jittable_single_program():
    model = llama_tiny(vocab_size=VOCAB, max_len=32)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, size=(2, 4)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    from functools import partial

    jitted = jax.jit(partial(generate, model, max_new_tokens=6))
    a = jitted(params, prompt)
    b = generate(model, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_temperature_sampling_shapes_and_range():
    model = gpt_tiny(vocab_size=VOCAB, max_len=32)
    prompt = jnp.zeros((3, 2), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    out = generate(
        model, params, prompt, max_new_tokens=5,
        temperature=1.0, top_k=10, rng=jax.random.PRNGKey(7),
    )
    assert out.shape == (3, 7)
    gen = np.asarray(out[:, 2:])
    assert gen.min() >= 0 and gen.max() < VOCAB
    # seeded -> deterministic
    out2 = generate(
        model, params, prompt, max_new_tokens=5,
        temperature=1.0, top_k=10, rng=jax.random.PRNGKey(7),
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_overflow_rejected():
    model = gpt_tiny(vocab_size=VOCAB, max_len=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, max_new_tokens=10)


def test_gqa_cache_is_kv_width():
    """The cache stores Hkv heads, not the full query-head count."""

    from tf_operator_tpu.models.decode import init_cache

    model = llama_tiny(vocab_size=VOCAB, max_len=32, n_kv_heads=2)
    cache = init_cache(model, batch_size=3)
    ck = cache["layer_0"]["self_attn"]["cached_key"]
    assert ck.shape == (3, 2, 32, 32)  # [B, Hkv, max_len, D]


def test_unsupported_family_rejected_cleanly():
    from tf_operator_tpu.models import bert_tiny, t5_tiny

    prompt = jnp.zeros((1, 2), jnp.int32)
    for model in (
        t5_tiny(vocab_size=VOCAB),  # needs encoder ids
        bert_tiny(vocab_size=VOCAB),  # bidirectional encoder
    ):
        with pytest.raises(NotImplementedError, match="decode is supported"):
            generate(model, {}, prompt, max_new_tokens=2)


class TestChunkedServingDecoder:
    """Compile-bounded serving decode (VERDICT r3 next #9): exact
    parity with generate() at a logarithmic compile budget."""

    def _setup(self, max_len=128):
        from tf_operator_tpu.models.decode import ChunkedServingDecoder

        model = llama_tiny(vocab_size=VOCAB, max_len=max_len)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        return model, params, ChunkedServingDecoder(model, params)

    def test_chunked_prefill_matches_one_shot(self):
        """Binary-decomposed prefill through the cache equals
        generate()'s one-shot prefill — for awkward prompt lengths
        (37 = 32+4+1).  Trains briefly first: different chunk shapes
        compile to different XLA programs whose fp reassociation can
        flip greedy argmax on near-tied INIT logits (benign, but an
        exact-token compare needs real margins — same discipline as
        test_trainer_sharded_generate_matches_gathered)."""

        from tf_operator_tpu.models import llama_loss
        from tf_operator_tpu.models.decode import ChunkedServingDecoder
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
        from tf_operator_tpu.runtime.harness import gather_params

        mesh = make_mesh({"dp": 8})
        r = np.random.RandomState(0)
        ids = r.randint(0, VOCAB, size=(8, 80)).astype(np.int32)
        tr = Trainer(
            llama_tiny(vocab_size=VOCAB, max_len=128, mesh=mesh),
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            llama_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        for _ in range(12):
            tr.train_step(tr.shard_batch({"input_ids": ids}))
        params = gather_params(tr)
        model = llama_tiny(vocab_size=VOCAB, max_len=128)
        dec = ChunkedServingDecoder(model, params)

        for p_len, n_new in ((1, 7), (5, 7), (37, 7), (64, 7)):
            prompt = jnp.asarray(r.randint(0, VOCAB, size=(2, p_len)), jnp.int32)
            a = dec.generate(prompt, max_new_tokens=n_new)
            b = generate(model, params, prompt, max_new_tokens=n_new)
            assert_decode_equiv_up_to_ties(model, params, a, b)
        # longer/awkward prompts: assert the MATH (chunked prefill's
        # last-position logits vs one-shot) with bf16 tolerance — exact
        # greedy-token chains over many steps amplify benign program-
        # level fp reassociation into tie-flips and say nothing extra
        from tf_operator_tpu.models.decode import _init_cache_for

        for p_len in (65, 127):
            prompt = jnp.asarray(r.randint(0, VOCAB, size=(1, p_len)), jnp.int32)
            cache, off, last = _init_cache_for(dec.dmodel, 1), 0, None
            for w in dec._chunks(p_len):
                cache, last = dec._prefill_fn(w)(
                    params, cache, prompt[:, off : off + w]
                )
                off += w
            _, one_shot = dec._prefill_fn(p_len)(
                params, _init_cache_for(dec.dmodel, 1), prompt
            )
            np.testing.assert_allclose(
                np.asarray(last), np.asarray(one_shot), rtol=0.02, atol=0.1
            )

    def test_overrun_budget_keeps_prefix_exact(self):
        """When the power-of-two budget overruns the cache (p + budget >
        max_len), the clamped tail writes must not corrupt the kept
        tokens: a request whose budget overruns and one whose budget
        doesn't produce the SAME leading tokens (the per-step decode
        program is identical; only discarded steps differ)."""

        model, params, dec = self._setup(max_len=128)
        prompt = jnp.asarray(
            np.random.RandomState(2).randint(0, VOCAB, size=(1, 66)), jnp.int32
        )
        a = dec.generate(prompt, 62)  # budget 64: write stream clamps at the edge
        b = dec.generate(prompt, 30)  # budget 32: no overrun
        np.testing.assert_array_equal(np.asarray(a[:, : 66 + 30]), np.asarray(b))

    def test_sampling_deterministic_and_in_range(self):
        model, params, dec = self._setup(max_len=64)
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(0, VOCAB, size=(1, 13)), jnp.int32
        )
        key = jax.random.PRNGKey(3)
        a = dec.generate(prompt, 6, temperature=0.8, top_k=8, rng=key)
        b = dec.generate(prompt, 6, temperature=0.8, top_k=8, rng=key)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        gen = np.asarray(a[:, 13:])
        assert gen.shape == (1, 6)
        assert gen.min() >= 0 and gen.max() < VOCAB

    def test_compile_count_bounded_across_random_lengths(self):
        """50 random-length requests stay within the logarithmic
        program budget: <= log2(max_len)+1 prefill chunks plus one
        decode loop per (budget bucket, sampling config)."""

        _, _, dec = self._setup(max_len=128)
        r = np.random.RandomState(7)
        budgets = set()
        for _ in range(50):
            # full valid range INCLUDING p near max_len, where the
            # budget overruns the cache — keys must stay powers of two
            p_len = int(r.randint(1, 120))
            n_new = int(r.randint(1, 128 - p_len + 1))
            prompt = jnp.asarray(r.randint(0, VOCAB, size=(1, p_len)), jnp.int32)
            out = dec.generate(prompt, n_new)
            assert out.shape == (1, p_len + n_new)
            budgets.add(1 << (n_new - 1).bit_length())
        # greedy requests with different top_k normalise onto ONE key
        prompt = jnp.asarray(r.randint(0, VOCAB, size=(1, 8)), jnp.int32)
        before = dec.compile_count
        dec.generate(prompt, 4, top_k=4)
        dec.generate(prompt, 4, top_k=9)
        assert dec.compile_count <= before + 1
        bound = 8 + len(budgets) + 1  # prefill chunks (2^0..2^7) + loops
        assert dec.compile_count <= bound, (dec.compile_count, bound)
        assert dec.compile_count < 50  # emphatically not one-per-request

    def test_concurrent_requests_thread_safe(self):
        """serve_lm fronts the decoder with ThreadingHTTPServer: cache
        bookkeeping must survive concurrent request threads (the LRU
        mutates on every call)."""

        import concurrent.futures

        _, _, dec = self._setup(max_len=64)
        r = np.random.RandomState(5)
        prompts = [
            jnp.asarray(r.randint(0, VOCAB, size=(1, int(r.randint(1, 40)))), jnp.int32)
            for _ in range(24)
        ]

        def one(prompt):
            out = dec.generate(prompt, 5)
            assert out.shape[1] == prompt.shape[1] + 5
            return int(out[0, -1])

        with concurrent.futures.ThreadPoolExecutor(max_workers=8) as ex:
            toks = list(ex.map(one, prompts))
        assert all(0 <= t < VOCAB for t in toks)
        # tight: 6 chunk widths (2^0..2^5 for p in 1..39) + ONE loop —
        # any duplicate compile from a cache race trips this
        assert dec.compile_count <= 6 + 1, dec.compile_count

    def test_validation(self):
        _, _, dec = self._setup(max_len=32)
        prompt = jnp.zeros((1, 4), jnp.int32)
        with pytest.raises(ValueError, match="temperature"):
            dec.generate(prompt, 4, temperature=-0.5)
        with pytest.raises(ValueError, match="max_len"):
            dec.generate(prompt, 40)
        with pytest.raises(ValueError, match="at least one token"):
            dec.generate(jnp.zeros((1, 0), jnp.int32), 4)


class TestMoeDecode:
    """Routed-expert serving (VERDICT r3 weak #6 / next #7)."""

    def test_decode_routing_is_dropless(self):
        """Training uses fixed capacity buckets that may drop tokens;
        the decode variant must not.  Rig the router so every token
        picks expert 0: under the droppy training config later tokens
        fall off the bucket (zero FFN output rows); the decode config
        admits all of them."""

        from tf_operator_tpu.models.moe import MoeConfig, MoeMlp
        from tf_operator_tpu.models.transformer import TransformerConfig
        import dataclasses as dc

        base = TransformerConfig(
            vocab_size=32, hidden=16, n_heads=2, head_dim=8,
            n_layers=1, mlp_dim=32, max_len=64,
        )
        # E=8, cf=0.25 -> training capacity max(int(s/16), 4) = 4 at s=24
        moe_train = MoeConfig(base=base, num_experts=8, capacity_factor=0.25)
        moe_decode = dc.replace(moe_train, base=dc.replace(base, decode=True))
        s = 24
        x = jnp.asarray(
            np.random.RandomState(0).rand(1, s, 16), jnp.float32
        )
        params = MoeMlp(moe_train).init(jax.random.PRNGKey(0), x)["params"]
        # router kernel [H, E]: huge bias toward expert 0
        rigged = jax.tree_util.tree_map(lambda p: p, params)
        kernel = np.zeros((16, 8), np.float32)
        kernel[:, 0] = 10.0
        rigged["router"]["kernel"] = jnp.asarray(kernel)

        out_train = MoeMlp(moe_train).apply({"params": rigged}, x)
        out_decode = MoeMlp(moe_decode).apply({"params": rigged}, x)
        # token rows past the capacity-4 bucket get NO expert output in
        # training mode; decode mode serves every row
        train_rows = np.abs(np.asarray(out_train[0])).sum(-1)
        decode_rows = np.abs(np.asarray(out_decode[0])).sum(-1)
        assert (train_rows[:4] > 1e-6).all()
        assert (train_rows[4:] < 1e-6).all(), "tokens past capacity must drop"
        assert (decode_rows > 1e-6).all(), "decode must be dropless"

    def test_moe_cache_and_pos_index(self):
        from tf_operator_tpu.models import moe_tiny
        from tf_operator_tpu.models.decode import init_cache

        model = moe_tiny(vocab_size=VOCAB, max_len=32)
        cache = init_cache(model, batch_size=2)
        ck = cache["layer_0"]["self_attn"]["cached_key"]
        assert ck.shape == (2, 4, 32, 32)  # [B, H, max_len, D]
        assert int(cache["pos_index"]) == 0

    def test_trainer_generate_moe_ep_sharded(self):
        """trainer.generate works for an ep-sharded MoE (the serving
        path VERDICT r3 weak #6 said was missing)."""

        from tf_operator_tpu.models import moe_lm_loss, moe_tiny
        from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh

        mesh = make_mesh({"dp": 4, "ep": 2})
        ids = np.random.RandomState(3).randint(0, VOCAB, size=(8, 24)).astype(np.int32)
        tr = Trainer(
            moe_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh),
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            moe_lm_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        for _ in range(6):
            tr.train_step(tr.shard_batch({"input_ids": ids}))
        prompt = jnp.asarray(ids[:2, :6])
        out = tr.generate(prompt, max_new_tokens=6)
        assert out.shape == (2, 12)
        gen = np.asarray(out[:, 6:])
        assert gen.min() >= 0 and gen.max() < VOCAB
        np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))


def test_temperature_without_rng_rejected():
    model = gpt_tiny(vocab_size=VOCAB, max_len=16)
    prompt = jnp.zeros((1, 2), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_new_tokens=2, temperature=0.7)


def test_trainer_sharded_generate_matches_gathered():
    """Tensor-parallel decoding: trainer.generate runs on the live
    sharded params (no host gather) and must equal generation from the
    gathered copy."""

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.runtime.harness import gather_params

    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2})
    ids = np.random.RandomState(0).randint(0, VOCAB, size=(4, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    # train enough that logits aren't init noise (greedy argmax on
    # near-ties would make exact token equality reduction-order brittle)
    for _ in range(12):
        tr.train_step(tr.shard_batch({"input_ids": ids}))

    prompt = jnp.asarray(ids[:2, :6])
    sharded_out = tr.generate(prompt, max_new_tokens=8)

    params = gather_params(tr)
    plain_model = llama_tiny(vocab_size=VOCAB, max_len=32)
    gathered_out = generate(plain_model, params, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(sharded_out[:, :6]), np.asarray(prompt))
    # sharded matmuls sum partials in a different order than the
    # single-device path; 12 training steps give the argmax real
    # margins, so the token streams should agree exactly (an early
    # tie-flip would cascade — a fractional threshold is fake precision)
    np.testing.assert_array_equal(np.asarray(sharded_out), np.asarray(gathered_out))


def test_export_then_serve(tmp_path):
    """train -> export params-only artifact -> load host-local -> the
    served generation matches the live sharded one."""

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import (
        Trainer,
        TrainerConfig,
        export_params,
        load_params,
        make_mesh,
    )

    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2})
    ids = np.random.RandomState(1).randint(0, VOCAB, size=(4, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    for _ in range(10):
        tr.train_step(tr.shard_batch({"input_ids": ids}))

    out_dir = str(tmp_path / "export")
    export_params(tr, out_dir)
    export_params(tr, out_dir)  # stable serving path: re-export overwrites
    served = load_params(out_dir)

    prompt = jnp.asarray(ids[:2, :6])
    live = tr.generate(prompt, max_new_tokens=6)
    plain = llama_tiny(vocab_size=VOCAB, max_len=32)
    from_artifact = generate(plain, served, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(live), np.asarray(from_artifact))


class TestRollingWindowCache:
    """Sliding-window decode with a ROLLING cache: O(window) serving
    memory instead of O(max_len) — the decode counterpart of the banded
    training kernels.  Slots wrap circularly; per-slot absolute
    positions keep the mask exact across wraps."""

    def test_cache_is_window_sized(self):
        from tf_operator_tpu.models.decode import init_cache

        model = llama_tiny(vocab_size=VOCAB, max_len=128, window=16, n_kv_heads=2)
        cache = init_cache(model, batch_size=3)
        layer = cache["layer_0"]["self_attn"]
        assert layer["cached_key"].shape == (3, 2, 16, 32)  # window, not max_len
        assert layer["cached_pos"].shape == (16,)
        assert int(layer["cached_pos"][0]) == -1  # empty sentinel

    @pytest.mark.parametrize("p_len", [5, 8, 21])
    def test_windowed_cached_matches_full_recompute(self, p_len):
        """Generation crosses the wrap boundary (window=8, positions
        run past 8): tokens must equal the full-recompute windowed
        reference exactly — including p_len=21, where the prompt itself
        prefills through three window-sized chunks.  f32 so benign
        program-level fp noise can't flip near-tied argmax on init
        params (rolling verified to ~1e-6 of the reference)."""

        model = llama_tiny(
            vocab_size=VOCAB, max_len=64, window=8, dtype=jnp.float32
        )
        prompt = jnp.asarray(
            np.random.RandomState(1).randint(0, VOCAB, size=(2, p_len)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(2), prompt)["params"]
        out = generate(model, params, prompt, max_new_tokens=8)
        ref = _reference_greedy(model, params, prompt, 8)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_chunked_decoder_caps_widths_at_window(self):
        from tf_operator_tpu.models.decode import ChunkedServingDecoder

        model = llama_tiny(vocab_size=VOCAB, max_len=128, window=8)
        prompt = jnp.asarray(
            np.random.RandomState(3).randint(0, VOCAB, size=(1, 37)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        dec = ChunkedServingDecoder(model, params)
        assert max(dec._chunks(37)) <= 8  # rolling cache bound per apply
        out = dec.generate(prompt, 6)
        ref = generate(model, params, prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_windowed_artifact_serves_with_rolling_cache(self, tmp_path):
        """export → model.json → rebuild → serve: a windowed artifact
        decodes through the O(window) rolling cache end to end."""

        from tf_operator_tpu.models import llama_loss
        from tf_operator_tpu.models.decode import ChunkedServingDecoder, init_cache
        from tf_operator_tpu.models.registry import model_from_description
        from tf_operator_tpu.parallel import (
            Trainer,
            TrainerConfig,
            export_params,
            load_model_description,
            load_params,
            make_mesh,
        )

        mesh = make_mesh({"dp": 8})
        ids = np.random.RandomState(6).randint(0, VOCAB, size=(8, 48)).astype(np.int32)
        tr = Trainer(
            llama_tiny(vocab_size=VOCAB, max_len=64, window=8, mesh=mesh),
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            llama_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        tr.train_step(tr.shard_batch({"input_ids": ids}))
        art = str(tmp_path / "wart")
        export_params(tr, art)
        desc = load_model_description(art)
        assert desc["config"]["window"] == 8
        model = model_from_description(desc)
        # the rebuilt server model really uses the rolling cache
        ck = init_cache(model, 1)["layer_0"]["self_attn"]["cached_key"]
        assert ck.shape[2] == 8  # window slots, not max_len=64
        dec = ChunkedServingDecoder(model, load_params(art))
        prompt = jnp.asarray(ids[:1, :20])
        out = dec.generate(prompt, 6)
        assert out.shape == (1, 26)
        gen = np.asarray(out[:, 20:])
        assert gen.min() >= 0 and gen.max() < VOCAB

    def test_oversized_single_apply_rejected(self):
        import dataclasses

        model = llama_tiny(vocab_size=VOCAB, max_len=64, window=8)
        dmodel = type(model)(
            dataclasses.replace(model.cfg, decode=True, dropout=0.0)
        )
        ids = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="window"):
            dmodel.init(jax.random.PRNGKey(0), ids)


class TestModelRegistry:
    """Self-describing artifacts (models/registry.py): export writes
    model.json; the serving side reconstructs the exact architecture."""

    def test_roundtrip_families(self):
        import dataclasses
        import json

        from tf_operator_tpu.models import bert_tiny, moe_tiny
        from tf_operator_tpu.models.registry import (
            describe_model,
            model_from_description,
        )

        for model in (
            gpt_tiny(vocab_size=VOCAB, max_len=32),
            llama_tiny(vocab_size=VOCAB, max_len=32, n_kv_heads=2),
            moe_tiny(vocab_size=VOCAB, max_len=32, num_experts=4),
        ):
            d = describe_model(model)
            json.dumps(d)  # must be JSON-serializable as-is
            m2 = model_from_description(d)
            assert type(m2) is type(model)
            assert dataclasses.replace(m2.cfg, mesh=None) == dataclasses.replace(
                model.cfg, mesh=None
            )
        # moe auxiliary knobs survive
        moe = moe_tiny(vocab_size=VOCAB, max_len=16, num_experts=8)
        m2 = model_from_description(describe_model(moe))
        assert m2.moe.num_experts == 8
        assert m2.moe.capacity_factor == moe.moe.capacity_factor
        # non-decoder families have no serving description
        assert describe_model(bert_tiny(vocab_size=VOCAB)) is None

    def test_export_writes_description_and_serves_from_it(self, tmp_path):
        from tf_operator_tpu.models import llama_loss
        from tf_operator_tpu.models.registry import model_from_description
        from tf_operator_tpu.parallel import (
            Trainer,
            TrainerConfig,
            export_params,
            load_model_description,
            load_params,
            make_mesh,
        )

        mesh = make_mesh({"dp": 8})
        ids = np.random.RandomState(4).randint(0, VOCAB, size=(8, 24)).astype(np.int32)
        tr = Trainer(
            llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh, n_kv_heads=2),
            TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
            mesh,
            llama_loss,
            {"input_ids": ids},
            init_args=(ids,),
            shardings="logical",
        )
        for _ in range(6):
            tr.train_step(tr.shard_batch({"input_ids": ids}))
        art = str(tmp_path / "art")
        export_params(tr, art)
        desc = load_model_description(art)
        assert desc["family"] == "llama"
        assert desc["config"]["n_kv_heads"] == 2

        # the RECONSTRUCTED model + exported params generate exactly
        # what the live trainer generates
        model = model_from_description(desc)
        prompt = jnp.asarray(ids[:2, :6])
        params = load_params(art)
        from_desc = generate(model, params, prompt, max_new_tokens=6)
        live = tr.generate(prompt, max_new_tokens=6)
        # reconstructed-vs-live runs two distinct programs (single-
        # device generate vs the trainer's sharded path): exact up to
        # sub-noise argmax ties
        assert_decode_equiv_up_to_ties(model, params, from_desc, live)


def test_serve_lm_end_to_end(tmp_path):
    """train -> export -> serve over HTTP: the examples/serve_lm.py
    handler answers /generate with decoded text from the artifact."""

    import json
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import (
        Trainer, TrainerConfig, export_params, load_params, make_mesh,
    )

    mesh = make_mesh({"dp": 8})
    ids = np.random.RandomState(2).randint(0, 256, size=(8, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=256, max_len=64, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    for _ in range(3):
        tr.train_step(tr.shard_batch({"input_ids": ids}))
    art = str(tmp_path / "artifact")
    export_params(tr, art)

    from tests.testutil import load_serve_lm

    serve_lm = load_serve_lm()
    model = llama_tiny(vocab_size=256, max_len=64)
    handler = serve_lm.build_handler(model, load_params(art), max_len=64)
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "the worker ", "max_new_tokens": 8}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert out["prompt"] == "the worker "
        assert isinstance(out["sample"], str) and len(out["sample"]) == 8
        # health + error paths
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["ok"]
        # metrics surface: requests counted by status, latency
        # histogram populated, tokens-generated counter advanced
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'serve_requests_total{status="200"} 1' in text
        assert ('serve_request_seconds_count'
                '{model="unknown",route="/generate"} 1') in text
        # the SLO families: every request observes TTFT and
        # time-per-output-token, labeled by model+mode
        assert ('serve_ttft_seconds_count'
                '{mode="chunked",model="unknown"} 1') in text
        assert ('serve_time_per_output_token_seconds_count'
                '{mode="chunked",model="unknown"} 1') in text
        assert "serve_tokens_generated_total 8.0" in text
        assert "serve_prompt_cache_hits 0" in text
        assert "serve_decoder_compiles" in text
        # /slo: the summary endpoint over the same histogram families
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/slo", timeout=10) as r:
            slo = json.loads(r.read())
        assert slo["requests_ok"] == 1.0
        ttft_rows = slo["histograms"]["serve_ttft_seconds"]
        assert len(ttft_rows) == 1 and ttft_rows[0]["count"] == 1
        assert ttft_rows[0]["model"] == "unknown"
        # /debug/flightrecorder: JSONL rings, meta record first
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/flightrecorder", timeout=10
        ) as r:
            lines = r.read().decode().strip().splitlines()
        assert json.loads(lines[0])["type"] == "meta"
        # stop sequence: sample truncates at the first occurrence —
        # with a single-byte stop drawn FROM the full sample, the
        # truncation is verifiable exactly against the untruncated run
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "the worker ", "max_new_tokens": 8}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            full = json.loads(resp.read())["sample"]
        stop_ch = full[3]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": "the worker ", "max_new_tokens": 8,
                 "stop": stop_ch}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            cut = json.loads(resp.read())["sample"]
        assert cut == full[: full.index(stop_ch)]
        # ADVICE r3: top_k arriving as a JSON string must be cast (not
        # used raw as a compile key), including on the greedy path
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": "abc", "max_new_tokens": 4, "top_k": "8"}
            ).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert json.loads(resp.read())["sample"]
        # negative temperature (inverted distribution) is a 400
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps(
                {"prompt": "abc", "max_new_tokens": 4, "temperature": -1.0}
            ).encode(),
            method="POST",
        )
        try:
            urllib.request.urlopen(req, timeout=10)
            raise AssertionError("negative temperature not rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 400
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "x" * 100, "max_new_tokens": 100}).encode(),
            method="POST",
        )
        try:
            urllib.request.urlopen(bad, timeout=10)
            raise AssertionError("overlong request not rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.shutdown()
