"""KV-cache generation: cached decode must equal full-recompute greedy."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import generate, gpt_tiny, llama_tiny

VOCAB = 128


def _reference_greedy(model, params, prompt, n):
    """No-cache reference: rerun the full forward on the growing
    sequence each step and argmax the last position."""

    ids = prompt
    for _ in range(n):
        logits = model.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_cached_greedy_matches_full_recompute(family):
    make = gpt_tiny if family == "gpt" else llama_tiny
    model = make(vocab_size=VOCAB, max_len=64)
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, VOCAB, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt)["params"]
    out = generate(model, params, prompt, max_new_tokens=8)
    ref = _reference_greedy(model, params, prompt, 8)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_is_jittable_single_program():
    model = llama_tiny(vocab_size=VOCAB, max_len=32)
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, VOCAB, size=(2, 4)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    from functools import partial

    jitted = jax.jit(partial(generate, model, max_new_tokens=6))
    a = jitted(params, prompt)
    b = generate(model, params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_temperature_sampling_shapes_and_range():
    model = gpt_tiny(vocab_size=VOCAB, max_len=32)
    prompt = jnp.zeros((3, 2), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    out = generate(
        model, params, prompt, max_new_tokens=5,
        temperature=1.0, top_k=10, rng=jax.random.PRNGKey(7),
    )
    assert out.shape == (3, 7)
    gen = np.asarray(out[:, 2:])
    assert gen.min() >= 0 and gen.max() < VOCAB
    # seeded -> deterministic
    out2 = generate(
        model, params, prompt, max_new_tokens=5,
        temperature=1.0, top_k=10, rng=jax.random.PRNGKey(7),
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))


def test_overflow_rejected():
    model = gpt_tiny(vocab_size=VOCAB, max_len=16)
    prompt = jnp.zeros((1, 10), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="max_len"):
        generate(model, params, prompt, max_new_tokens=10)


def test_gqa_cache_is_kv_width():
    """The cache stores Hkv heads, not the full query-head count."""

    from tf_operator_tpu.models.decode import init_cache

    model = llama_tiny(vocab_size=VOCAB, max_len=32, n_kv_heads=2)
    cache = init_cache(model, batch_size=3)
    ck = cache["layer_0"]["self_attn"]["cached_key"]
    assert ck.shape == (3, 2, 32, 32)  # [B, Hkv, max_len, D]


def test_unsupported_family_rejected_cleanly():
    from tf_operator_tpu.models import bert_tiny, moe_tiny, t5_tiny

    prompt = jnp.zeros((1, 2), jnp.int32)
    for model in (
        moe_tiny(vocab_size=VOCAB, max_len=16),  # routing is training-shaped
        t5_tiny(vocab_size=VOCAB),  # needs encoder ids
        bert_tiny(vocab_size=VOCAB),  # bidirectional encoder
    ):
        with pytest.raises(NotImplementedError, match="decode is supported"):
            generate(model, {}, prompt, max_new_tokens=2)


def test_temperature_without_rng_rejected():
    model = gpt_tiny(vocab_size=VOCAB, max_len=16)
    prompt = jnp.zeros((1, 2), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    with pytest.raises(ValueError, match="rng"):
        generate(model, params, prompt, max_new_tokens=2, temperature=0.7)


def test_trainer_sharded_generate_matches_gathered():
    """Tensor-parallel decoding: trainer.generate runs on the live
    sharded params (no host gather) and must equal generation from the
    gathered copy."""

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import Trainer, TrainerConfig, make_mesh
    from tf_operator_tpu.runtime.harness import gather_params

    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2})
    ids = np.random.RandomState(0).randint(0, VOCAB, size=(4, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    # train enough that logits aren't init noise (greedy argmax on
    # near-ties would make exact token equality reduction-order brittle)
    for _ in range(12):
        tr.train_step(tr.shard_batch({"input_ids": ids}))

    prompt = jnp.asarray(ids[:2, :6])
    sharded_out = tr.generate(prompt, max_new_tokens=8)

    params = gather_params(tr)
    plain_model = llama_tiny(vocab_size=VOCAB, max_len=32)
    gathered_out = generate(plain_model, params, prompt, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(sharded_out[:, :6]), np.asarray(prompt))
    # sharded matmuls sum partials in a different order than the
    # single-device path; 12 training steps give the argmax real
    # margins, so the token streams should agree exactly (an early
    # tie-flip would cascade — a fractional threshold is fake precision)
    np.testing.assert_array_equal(np.asarray(sharded_out), np.asarray(gathered_out))


def test_export_then_serve(tmp_path):
    """train -> export params-only artifact -> load host-local -> the
    served generation matches the live sharded one."""

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import (
        Trainer,
        TrainerConfig,
        export_params,
        load_params,
        make_mesh,
    )

    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2})
    ids = np.random.RandomState(1).randint(0, VOCAB, size=(4, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=VOCAB, max_len=32, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    for _ in range(10):
        tr.train_step(tr.shard_batch({"input_ids": ids}))

    out_dir = str(tmp_path / "export")
    export_params(tr, out_dir)
    export_params(tr, out_dir)  # stable serving path: re-export overwrites
    served = load_params(out_dir)

    prompt = jnp.asarray(ids[:2, :6])
    live = tr.generate(prompt, max_new_tokens=6)
    plain = llama_tiny(vocab_size=VOCAB, max_len=32)
    from_artifact = generate(plain, served, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(live), np.asarray(from_artifact))


def test_serve_lm_end_to_end(tmp_path):
    """train -> export -> serve over HTTP: the examples/serve_lm.py
    handler answers /generate with decoded text from the artifact."""

    import json
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    from tf_operator_tpu.models import llama_loss, llama_tiny
    from tf_operator_tpu.parallel import (
        Trainer, TrainerConfig, export_params, load_params, make_mesh,
    )

    mesh = make_mesh({"dp": 8})
    ids = np.random.RandomState(2).randint(0, 256, size=(8, 24)).astype(np.int32)
    tr = Trainer(
        llama_tiny(vocab_size=256, max_len=64, mesh=mesh),
        TrainerConfig(learning_rate=1e-2, optimizer="sgd"),
        mesh,
        llama_loss,
        {"input_ids": ids},
        init_args=(ids,),
        shardings="logical",
    )
    for _ in range(3):
        tr.train_step(tr.shard_batch({"input_ids": ids}))
    art = str(tmp_path / "artifact")
    export_params(tr, art)

    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "serve_lm", os.path.join(os.path.dirname(__file__), "..", "examples", "serve_lm.py")
    )
    serve_lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve_lm)

    model = llama_tiny(vocab_size=256, max_len=64)
    handler = serve_lm.build_handler(model, load_params(art), max_len=64)
    server = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "the worker ", "max_new_tokens": 8}).encode(),
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=120) as resp:
            out = json.loads(resp.read())
        assert out["prompt"] == "the worker "
        assert isinstance(out["sample"], str) and len(out["sample"]) == 8
        # health + error paths
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["ok"]
        bad = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate",
            data=json.dumps({"prompt": "x" * 100, "max_new_tokens": 100}).encode(),
            method="POST",
        )
        try:
            urllib.request.urlopen(bad, timeout=10)
            raise AssertionError("overlong request not rejected")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        server.shutdown()
