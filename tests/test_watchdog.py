"""Stall watchdog (utils/watchdog.py): deadline sweep unit tests plus
the freeze-the-train-loop e2e — a wedged harness must produce a
flight-recorder dump naming the stalled phase's last spans (ISSUE 5
acceptance)."""

import json
import threading
import time

import pytest

from tf_operator_tpu.utils.flight import FlightRecorder
from tf_operator_tpu.utils.metrics import Metrics
from tf_operator_tpu.utils.trace import Tracer
from tf_operator_tpu.utils.watchdog import Watchdog, thread_stacks


class TestDeadlineSweep:
    def test_fresh_heartbeat_not_stalled(self):
        dog = Watchdog(metrics=Metrics())
        dog.register("a", deadline=5.0)
        assert dog.check_once() == []

    def test_missed_deadline_fires_once_per_episode(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        m = Metrics()
        dog = Watchdog(metrics=m, recorder=FlightRecorder())
        hb = dog.register("loop", deadline=0.01)
        hb.last -= 1.0  # simulate silence
        assert dog.check_once() == ["loop"]
        assert dog.check_once() == []  # same episode: no refire
        assert m.counter("watchdog_stall_total", heartbeat="loop") == 1.0
        assert len(dog.dumps) == 1

    def test_beat_ends_episode_and_next_stall_refires(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        m = Metrics()
        dog = Watchdog(metrics=m, recorder=FlightRecorder())
        hb = dog.register("loop", deadline=0.01)
        hb.last -= 1.0
        assert dog.check_once() == ["loop"]
        hb.beat()
        assert dog.check_once() == []  # recovered
        hb.last -= 1.0
        assert dog.check_once() == ["loop"]  # fresh episode
        assert m.counter("watchdog_stall_total", heartbeat="loop") == 2.0

    def test_heartbeat_captures_trace_id(self):
        tracer = Tracer(seed=11)
        dog = Watchdog()
        hb = dog.register("traced")
        with tracer.span("work"):
            hb.beat()
        assert hb.trace_id is not None and hb.trace_id.startswith("t")

    def test_unregister_silences(self):
        dog = Watchdog(metrics=Metrics(), recorder=FlightRecorder())
        hb = dog.register("gone", deadline=0.01)
        hb.last -= 1.0
        dog.unregister("gone")
        assert dog.check_once() == []

    def test_thread_stacks_names_this_test(self):
        text = thread_stacks()
        assert "test_thread_stacks_names_this_test" in text

    def test_background_thread_start_stop(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        m = Metrics()
        dog = Watchdog(metrics=m, recorder=FlightRecorder(),
                       check_interval=0.02)
        hb = dog.register("bg", deadline=0.05)
        dog.start()
        try:
            assert dog.running
            deadline = time.time() + 5.0
            while time.time() < deadline and not dog.dumps:
                time.sleep(0.02)  # stop beating: the monitor must fire
            assert dog.dumps, "background sweep never detected the stall"
            assert m.counter("watchdog_stall_total", heartbeat="bg") == 1.0
        finally:
            dog.stop()
        assert not dog.running
        assert hb.stalled


@pytest.mark.slow
class TestFreezeTheHarness:
    def test_frozen_train_loop_dumps_last_spans(self, tmp_path, monkeypatch):
        """The acceptance e2e: a train loop frozen mid-run (its data
        iterator hangs) stops heartbeating; the watchdog dumps the
        flight recorder, and the dump contains the stalled phase's
        last spans (train.step / data.load of the steps that DID
        run)."""

        monkeypatch.setenv("TPUJOB_FLIGHT_DIR", str(tmp_path))
        from tests.test_harness import FakeTrainer, _series
        from tf_operator_tpu.runtime.harness import train_loop
        from tf_operator_tpu.utils.metrics import StepSyncLedger

        m = Metrics()
        tracer = Tracer(seed=5)
        recorder = FlightRecorder()
        recorder.attach_tracer(tracer)
        recorder.attach_metrics(m)
        dog = Watchdog(metrics=m, recorder=recorder, check_interval=0.05)
        release = threading.Event()

        def batches():
            for i in range(4):
                yield {"x": i}
            release.wait(timeout=30.0)  # the freeze
            raise RuntimeError("unfrozen: end the thread")

        def run():
            try:
                train_loop(
                    FakeTrainer(_series(64)), batches(), 64,
                    steps_per_sync=2, assert_decreasing=False,
                    tracer=tracer, watchdog=dog,
                    sync_ledger=StepSyncLedger(metrics=m, tracer=tracer),
                )
            except RuntimeError:
                pass

        t = threading.Thread(target=run, daemon=True)
        t.start()
        dog.start()
        try:
            # the loop beats twice (2 windows of 2 steps), then hangs in
            # data.load; drop the deadline only after those beats landed
            deadline = time.time() + 10.0
            hb = None
            while time.time() < deadline:
                hb = dog.heartbeats().get("train.train")
                if hb is not None and hb.beats >= 1:
                    break
                time.sleep(0.02)
            assert hb is not None and hb.beats >= 1, "loop never started"
            hb.deadline = 0.2
            deadline = time.time() + 10.0
            while time.time() < deadline and not dog.dumps:
                time.sleep(0.05)
            assert dog.dumps, "watchdog never dumped on the frozen loop"
        finally:
            release.set()
            dog.stop()
            t.join(timeout=10.0)

        assert m.counter("watchdog_stall_total", heartbeat="train.train") == 1.0
        records = [json.loads(x) for x in open(dog.dumps[0])]
        span_names = {r["name"] for r in records if r["type"] == "span"}
        # the stalled phase's last spans: the completed steps' work
        assert "train.step" in span_names
        assert "data.load" in span_names
        # the stall postmortem carries every thread's stack
        stack_logs = [
            r for r in records
            if r["type"] == "log" and "thread stacks" in r["message"]
        ]
        assert stack_logs and "release.wait" in stack_logs[0]["fields"]["stacks"]
