"""Lint gate: stock autoscaling policies can never silently orphan
(ISSUE 7 satellite — the test_alert_rules_lint.py pattern extended to
the act layer).

An autoscaling policy binds signals by NAME: an ``alert`` binding names
a rule in the default alert set, a ``gauge`` binding names an emitted
metric family.  Renaming either would leave the policy evaluating a
signal nobody drives — it would simply never scale again, the act-layer
twin of an orphaned alert rule.  This gate reuses the alert lint's AST
collector (every literal metric-family write in the package + examples)
plus the default rule set's names, and asserts every stock policy's
bindings resolve; it also pins the structural validator against the
stock policies so a bad template can never ship.
"""

import pytest

from tests.test_alert_rules_lint import collect_emitted_families
from tests.testutil import new_job
from tf_operator_tpu.api.types import AutoscalingSpec, ReplicaType
from tf_operator_tpu.api.validation import validate
from tf_operator_tpu.controller.autoscaler import (
    default_serving_policy,
    default_slice_training_policy,
    default_training_policy,
)
from tf_operator_tpu.utils.alerts import default_rules


def stock_policies():
    return [
        default_serving_policy(),
        default_training_policy(),
        default_slice_training_policy(),
    ]


def test_stock_policy_signals_resolve_to_live_rules_or_families():
    families = collect_emitted_families()
    rule_names = {r.name for r in default_rules()}
    problems = []
    for pol in stock_policies():
        for sig in pol.signals:
            if sig.kind == "alert":
                if sig.name not in rule_names:
                    problems.append(
                        f"policy {pol.mode}/{pol.replica_type.value} binds "
                        f"alert {sig.name!r} which is not in the default "
                        "rule set (utils/alerts.default_rules)"
                    )
            elif sig.kind == "gauge":
                if sig.name not in families:
                    problems.append(
                        f"policy {pol.mode}/{pol.replica_type.value} binds "
                        f"gauge {sig.name!r} which no code emits"
                    )
            else:
                problems.append(
                    f"policy {pol.mode}/{pol.replica_type.value} has "
                    f"unknown signal kind {sig.kind!r}"
                )
    assert not problems, "orphaned autoscaling bindings:\n  " + "\n  ".join(
        problems
    )


def test_stock_policies_pass_spec_validation():
    for pol in stock_policies():
        if pol.replica_type is ReplicaType.TPU_SLICE:
            job = new_job(name="lint", tpu_slice=2, tpu_topology="v5e-4")
        else:
            job = new_job(name="lint", worker=2)
        job.spec.autoscaling = AutoscalingSpec(policies=[pol])
        validate(job)  # raises on a structurally bad template


def test_autoscaler_metric_families_are_emitted_with_expected_labels():
    """The autoscaler's own exposition (the families dashboards and
    future alert rules may bind) is collectable by the AST gate — so
    THOSE can be rule/policy targets without orphaning either."""

    families = collect_emitted_families()
    assert "direction" in families["autoscaler_decisions_total"]
    assert "reason" in families["autoscaler_skipped_total"]
    assert {"job", "replicaType"} <= families["autoscaler_desired_replicas"]
    assert "autoscaler_evaluations_total" in families
    assert "tpujob_reshards_total" in families


def test_lint_catches_a_renamed_signal():
    """Planted orphan: a policy binding a gauge nobody emits must be
    reported (the gate's own regression test)."""

    families = collect_emitted_families()
    pol = default_serving_policy()
    pol.signals[1].name = "metric_that_was_renamed_depth"
    assert pol.signals[1].name not in families


def test_serving_policy_binds_blocks_free_pressure():
    """ISSUE 8: the stock serving policy is rebound to blocks-free
    pressure — its gauge binding must name the paged pool's emitted
    ``kv_blocks_pressure`` family (with the declared {model, replica}
    keys) and trigger BEFORE the kv-blocks-pressure alert pages, so
    the autoscaler acts on real memory headroom first."""

    families = collect_emitted_families()
    pol = default_serving_policy()
    gauge_sigs = [s for s in pol.signals if s.kind == "gauge"]
    assert any(s.name == "kv_blocks_pressure" for s in gauge_sigs)
    assert {"model", "replica"} <= families["kv_blocks_pressure"]
    pressure_rule = next(
        r for r in default_rules() if r.name == "kv-blocks-pressure"
    )
    (sig,) = [s for s in gauge_sigs if s.name == "kv_blocks_pressure"]
    assert sig.threshold <= pressure_rule.threshold


def test_paged_serving_families_are_emitted_with_expected_labels():
    """The ISSUE 8 metric families any rule/policy/dashboard may bind:
    kv_blocks_* gauges carry {model, replica} plus — since ISSUE 13 —
    the {role} key the disaggregated policies filter on; the unified
    prefix cache counters carry {mode} — a rename fails tier-1 here
    before it orphans a binding silently."""

    families = collect_emitted_families()
    for fam in (
        "kv_blocks_free",
        "kv_blocks_total",
        "kv_blocks_in_use",
        "kv_blocks_queued_demand",  # ISSUE 10: mid-burst demand ramp
        "kv_blocks_pressure",
    ):
        assert {"model", "replica", "role"} <= families[fam], fam
    for fam in (
        "serve_prefix_cache_hits_total",
        "serve_prefix_cache_misses_total",
        "serve_prefix_cache_evictions_total",
    ):
        assert "mode" in families[fam], fam


def test_serving_policy_binds_preemption_rate():
    """ISSUE 12: the stock serving policy must carry the thrash
    signal — its alert binding names the live ``serve-preemption-rate``
    rule, which in turn references the emitted
    ``serve_preemptions_total{model,tier}`` family — so sustained
    swapping scales replicas out before interactive TTFT burns."""

    families = collect_emitted_families()
    pol = default_serving_policy()
    alert_sigs = {s.name for s in pol.signals if s.kind == "alert"}
    assert "serve-preemption-rate" in alert_sigs
    rule = next(
        r for r in default_rules() if r.name == "serve-preemption-rate"
    )
    assert rule.metric == "serve_preemptions_total"
    assert rule.kind == "counter_increase"
    assert {"model", "tier"} <= families[rule.metric]


def test_swap_and_commit_families_are_emitted_with_expected_labels():
    """The ISSUE 12 families any rule/policy/dashboard may bind."""

    families = collect_emitted_families()
    assert "direction" in families["kv_swap_bytes_total"]
    for fam in ("kv_blocks_committed", "kv_blocks_reserved"):
        assert {"model", "replica"} <= families[fam], fam


def test_disaggregated_policies_bind_role_labeled_pressure():
    """ISSUE 13: the stock disaggregated policy pair scales the
    prefill (PS) and decode (WORKER) replica classes INDEPENDENTLY off
    ``kv_blocks_pressure{role=}``.  The gate pins: both role filters
    name label KEYS the emitting call sites declare, the gauge family
    is live, every role value is a real replica role, thresholds stay
    below the kv-blocks-pressure page, the decode class keeps the
    SLO/thrash alert bindings, and the pair passes admission on a
    PS+WORKER serving job."""

    from tf_operator_tpu.controller.autoscaler import (
        default_disaggregated_policies,
    )
    from tf_operator_tpu.models.batching import REPLICA_ROLES

    families = collect_emitted_families()
    pols = default_disaggregated_policies()
    assert len(pols) == 2
    rule_names = {r.name for r in default_rules()}
    pressure_rule = next(
        r for r in default_rules() if r.name == "kv-blocks-pressure"
    )
    roles_bound = set()
    for pol in pols:
        for sig in pol.signals:
            if sig.kind == "gauge":
                assert sig.name in families, sig.name
                assert set(sig.labels) <= families[sig.name], (
                    f"{pol.replica_type.value} filters on label keys "
                    f"{sorted(set(sig.labels) - families[sig.name])} "
                    f"never attached to {sig.name!r}"
                )
                role = sig.labels.get("role")
                assert role in REPLICA_ROLES, role
                roles_bound.add(role)
                assert sig.threshold <= pressure_rule.threshold
            else:
                assert sig.name in rule_names, sig.name
    assert roles_bound == {"prefill", "decode"}
    decode_pol = next(
        p for p in pols if p.replica_type is ReplicaType.WORKER
    )
    alert_sigs = {s.name for s in decode_pol.signals if s.kind == "alert"}
    assert {"serve-queue-wait-burn", "serve-preemption-rate"} <= alert_sigs

    job = new_job(name="disagg-lint", ps=1, worker=2)
    job.spec.autoscaling = AutoscalingSpec(policies=pols)
    validate(job)  # raises on a structurally bad template


def test_slice_training_policy_binds_gang_gauge_and_slice_set():
    """ISSUE 14: the stock slice-topology policy scales the TPU_SLICE
    replica set (whole slices are the shed unit) off the reconciler's
    ``tpujob_gang_waiting_replicas`` gauge — the signal a capacity
    shrink raises when the declared slice count no longer fits — plus
    the watchdog-stall alert.  The gate pins: the gauge family is
    emitted with the {job} key, the alert resolves in the default rule
    set, the mode is training (checkpoint-gated resizes), and the
    checkpoint gate is no looser than the stale alert."""

    families = collect_emitted_families()
    rule_names = {r.name for r in default_rules()}
    pol = default_slice_training_policy()
    assert pol.replica_type is ReplicaType.TPU_SLICE
    assert pol.mode == "training"
    gauge_sigs = [s for s in pol.signals if s.kind == "gauge"]
    assert any(
        s.name == "tpujob_gang_waiting_replicas" for s in gauge_sigs
    )
    assert "job" in families["tpujob_gang_waiting_replicas"]
    for s in pol.signals:
        if s.kind == "alert":
            assert s.name in rule_names, s.name
    stale_rule = next(
        r for r in default_rules() if r.name == "checkpoint-stale"
    )
    assert pol.max_checkpoint_age_seconds <= stale_rule.threshold


def test_train_dcn_families_are_emitted_with_fabric_label():
    """ISSUE 14: the multi-slice grad-sync accounting families any
    rule/policy/dashboard may bind — bytes, collective count, and
    measured sync seconds, each split by {fabric=ici|dcn}.  The bytes
    and collective counters are host-side per-dispatch writes in
    parallel/trainer.py; the seconds histogram is observed by the
    collectives sync probe (measure.py --section multislice)."""

    families = collect_emitted_families()
    for fam in (
        "train_dcn_bytes_total",
        "train_dcn_collectives_total",
        "train_dcn_sync_seconds",
    ):
        assert "fabric" in families[fam], fam


def test_fabric_families_are_emitted_with_expected_labels():
    """ISSUE 17: the cross-pod KV fabric families any rule/policy/
    dashboard may bind — publish-side catalog gauges/counters
    ({model}), pull-side outcomes ({model, outcome}) and failure
    reasons ({model, reason}), per-peer liveness ({peer}), and the
    migrate-bytes split by {direction, transport} that separates local
    arena traffic from wire pulls.  A rename fails tier-1 here before
    the fabric-peer-unreachable rule or a fabric panel orphans."""

    families = collect_emitted_families()
    assert "model" in families["kv_fabric_blocks"]
    assert "model" in families["kv_fabric_publishes_total"]
    assert {"model", "outcome"} <= families["kv_fabric_pulls_total"]
    assert {"model", "reason"} <= families["kv_fabric_pull_failures_total"]
    assert "peer" in families["kv_fabric_peer_up"]
    assert {"direction", "transport"} <= families["kv_migrate_bytes_total"]
    rule = next(
        r for r in default_rules() if r.name == "fabric-peer-unreachable"
    )
    assert rule.metric in families
    assert set(rule.labels) <= families[rule.metric]


def test_speculative_families_are_emitted_with_expected_labels():
    """ISSUE 18: the speculative paged serving counters any rule/
    policy/dashboard may bind — proposed draft tokens, accepted draft
    tokens, and rollback windows, each split by {model, tier} (the
    tier key is how a dashboard shows acceptance per SLO class, since
    speculation is tier-gated).  A rename fails tier-1 here before an
    acceptance-rate panel orphans silently."""

    families = collect_emitted_families()
    for fam in (
        "serve_spec_proposed_total",
        "serve_spec_accepted_total",
        "serve_spec_rollbacks_total",
    ):
        assert {"model", "tier"} <= families[fam], fam


def test_resize_gate_reads_the_federated_checkpoint_family():
    """ISSUE 15 satellite: the training resize gate's registry
    fallback (``job_checkpoint_age``) must read the FEDERATED
    ``checkpoint_last_success_unix{job=}`` series — a subprocess
    trainer pod's stamp, scraped into the operator registry, gates the
    resize; another job's stamp never does."""

    from tests.test_alert_rules_lint import collect_federated_families
    from tf_operator_tpu.controller.autoscaler import job_checkpoint_age
    from tf_operator_tpu.utils.metrics import Metrics

    families = collect_federated_families()
    assert {"job", "replica_type", "replica_index"} <= families[
        "checkpoint_last_success_unix"
    ]

    now = 1_700_000_000.0
    job = new_job(name="fed-gate", worker=2)
    m = Metrics()
    # only ANOTHER job's federated stamp: age must stay unknown
    m.set(
        "checkpoint_last_success_unix", now - 5.0,
        job="default/other", replica_type="worker", replica_index="0",
        slice="",
    )
    assert job_checkpoint_age(job, now, metrics=m) is None
    # this job's federated stamp: the age is its pod's
    m.set(
        "checkpoint_last_success_unix", now - 42.0,
        job=job.key, replica_type="worker", replica_index="0", slice="",
    )
    age = job_checkpoint_age(job, now, metrics=m)
    assert age is not None and abs(age - 42.0) < 1e-6


def test_cost_plane_veto_rules_resolve_in_default_rule_set():
    """ISSUE 20: the autoscaler refuses to scale — both directions —
    while a cost-plane rule fires (a recompiling or step-time-regressed
    fleet gives garbage signals; scaling on them thrashes).  The veto
    names rules by string, so each name must resolve in the default
    rule set or the veto silently never engages."""

    from tf_operator_tpu.controller.autoscaler import COST_PLANE_VETO_RULES

    rule_names = {r.name for r in default_rules()}
    assert set(COST_PLANE_VETO_RULES) <= rule_names
    assert set(COST_PLANE_VETO_RULES) == {
        "compile-storm", "step-time-regression",
    }


def test_stock_policy_checkpoint_gate_is_consistent_with_alert_rule():
    """The training policy's resize gate and the checkpoint-stale alert
    read the same stamp: the gate threshold must not be LOOSER than the
    alert threshold, or the autoscaler would happily resize a job whose
    checkpoint the alert layer already calls stale."""

    stale_rule = next(
        r for r in default_rules() if r.name == "checkpoint-stale"
    )
    pol = default_training_policy()
    assert pol.max_checkpoint_age_seconds <= stale_rule.threshold
